"""spfft_tpu.tuning: wisdom store contract + TUNED policy behavior.

Covers the safety ladder the subsystem promises (tuning module docstring):
serialization round-trip, corrupted-file and schema-version-mismatch
fallback, CPU-only trial skip (model fallback), and the wisdom cache-hit
guarantee — constructing the same plan twice runs trials exactly once, with
``plan.report()`` recording provenance and per-candidate trial timings.

CPU trials are explicitly allowed (``SPFFT_TPU_TUNE_CPU=1``) in the tests
that need them; the skip test leaves the knob unset to assert the default.
"""
import json

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    obs,
    tuning,
)
from spfft_tpu.errors import InvalidParameterError
from utils import assert_close

DIM = 8


@pytest.fixture(autouse=True)
def fresh_tuning(monkeypatch):
    """Isolate every test: no ambient wisdom (env or process memory), a
    1-repeat trial budget, and a clean metrics registry for trial counting."""
    tuning.clear_memory()
    monkeypatch.delenv(tuning.WISDOM_ENV, raising=False)
    monkeypatch.delenv(tuning.TUNE_CPU_ENV, raising=False)
    monkeypatch.delenv("SPFFT_TPU_POLICY", raising=False)
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "1")
    obs.enable()
    obs.clear()
    yield
    tuning.clear_memory()


def _triplets():
    return sp.create_spherical_cutoff_triplets(DIM, DIM, DIM, 0.8)


def _distributed(policy="tuned", **kwargs):
    return DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        DIM,
        DIM,
        DIM,
        _triplets(),
        mesh=sp.make_fft_mesh(2),
        policy=policy,
        **kwargs,
    )


def _trial_count() -> int:
    snap = obs.snapshot()
    return sum(
        v
        for k, v in snap["counters"].items()
        if k.startswith("tuning_trials_total")
    )


# ---- wisdom store ----------------------------------------------------------


def test_wisdom_roundtrip(tmp_path):
    path = tmp_path / "wisdom.json"
    store = tuning.WisdomStore(str(path))
    key = {"kind": "exchange", "dims": [8, 8, 8], "platform": "cpu"}
    entry = tuning.make_entry(
        key, {"exchange_type": "BUFFERED"}, [{"label": "BUFFERED", "ms": 1.0}]
    )
    store.record(key, entry)
    doc = json.loads(path.read_text())
    assert doc["schema"] == tuning.WISDOM_SCHEMA
    got = tuning.WisdomStore(str(path)).lookup(key)
    assert got["choice"] == {"exchange_type": "BUFFERED"}
    assert got["trials"] == entry["trials"]
    assert got["key"] == key
    # a different key misses; recording it preserves the first entry
    other = dict(key, dims=[16, 16, 16])
    assert store.lookup(other) is None
    store.record(other, tuning.make_entry(other, {"exchange_type": "UNBUFFERED"}, []))
    assert tuning.WisdomStore(str(path)).lookup(key)["choice"] == {
        "exchange_type": "BUFFERED"
    }


def test_corrupted_file_falls_back(tmp_path, monkeypatch):
    path = tmp_path / "wisdom.json"
    path.write_text("{not json")
    monkeypatch.setenv(tuning.WISDOM_ENV, str(path))
    # trials disallowed (CPU, no override): corruption must degrade to the
    # model policy, never raise out of plan construction
    t = _distributed()
    assert t._tuning["provenance"] == "model"
    assert "corrupt" in t._tuning["reason"]
    assert t._tuning["trials"] == []
    # the model fallback picks exactly what the model policy would
    assert t.exchange_type == _distributed(policy="default").exchange_type


def test_schema_version_mismatch_falls_back(tmp_path, monkeypatch):
    path = tmp_path / "wisdom.json"
    path.write_text(json.dumps({"schema": "spfft_tpu.tuning.wisdom/999", "entries": {}}))
    monkeypatch.setenv(tuning.WISDOM_ENV, str(path))
    t = _distributed()
    assert t._tuning["provenance"] == "model"
    assert "schema mismatch" in t._tuning["reason"]
    # re-measuring over a mismatched store rewrites it at the current schema
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t2 = _distributed()
    assert t2._tuning["provenance"] == "wisdom"
    assert json.loads(path.read_text())["schema"] == tuning.WISDOM_SCHEMA


def test_cpu_only_trial_skip_model_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    t = _distributed()  # SPFFT_TPU_TUNE_CPU unset -> no trials on CPU
    rec = t._tuning
    assert rec["policy"] == "tuned"
    assert rec["provenance"] == "model"
    assert rec["hit"] is False
    assert rec["trials"] == []
    assert _trial_count() == 0
    assert t.exchange_type == _distributed(policy="default").exchange_type
    # nothing was persisted: a skipped decision must not masquerade as wisdom
    assert not (tmp_path / "wisdom.json").exists()


# ---- cache-hit guarantee ---------------------------------------------------


def test_cache_hit_runs_zero_trials(tmp_path, monkeypatch):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t1 = _distributed()
    rec1 = t1._tuning
    assert rec1["provenance"] == "wisdom" and rec1["hit"] is False
    n1 = _trial_count()
    assert n1 >= 3  # one trial per candidate discipline
    # second construction of the SAME plan: wisdom hit, ZERO new trials
    t2 = _distributed()
    rec2 = t2._tuning
    assert rec2["provenance"] == "wisdom" and rec2["hit"] is True
    assert _trial_count() == n1
    assert t2.exchange_type == t1.exchange_type
    assert rec2["choice"] == rec1["choice"]
    # the hit still reports the persisted trial table
    assert rec2["trials"] and all("ms" in row for row in rec2["trials"])
    # plan card carries the full provenance record and stays schema-complete
    card = t2.report()
    assert card["policy"] == "tuned"
    assert card["tuning"]["provenance"] == "wisdom"
    assert card["tuning"]["trials"] == rec2["trials"]
    assert obs.validate_plan_card(card) == []
    # a tuned plan still transforms correctly (against the local oracle)
    trip = _triplets()
    rng = np.random.default_rng(0)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = sp.distribute_triplets(trip, 2, DIM)
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    vps = [np.asarray([lut[tuple(x)] for x in s]) for s in per_shard]
    local = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip
    ).backward(values)
    assert_close(t2.backward(vps), local)
    back = t2.forward(scaling=ScalingType.FULL)
    for r, v in enumerate(vps):
        assert_close(back[r], v)


def test_local_tuned_cache_hit(tmp_path, monkeypatch):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    trip = _triplets()
    t1 = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM,
        indices=trip, policy="tuned",
    )
    rec1 = t1._tuning
    assert rec1["provenance"] == "wisdom" and rec1["hit"] is False
    assert t1._engine == rec1["choice"]["engine"]
    labels = {row["label"] for row in rec1["trials"]}
    assert {"xla", "mxu", "mxu/dense-y"} <= labels
    n1 = _trial_count()
    t2 = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM,
        indices=trip, policy="tuned",
    )
    assert t2._tuning["hit"] is True
    assert _trial_count() == n1
    assert t2._engine == t1._engine
    assert obs.validate_plan_card(t2.report()) == []
    # tuned local plan keeps the numerics contract
    rng = np.random.default_rng(1)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    oracle = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip
    ).backward(values)
    assert_close(t2.backward(values), oracle)


def test_perf_knob_change_invalidates(tmp_path, monkeypatch):
    """Wisdom keyed under one ambient perf-knob state must not answer for
    another (wisdom.PERF_ENV_KNOBS rides in every key)."""
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t1 = _distributed()
    assert t1._tuning["hit"] is False
    monkeypatch.setenv("SPFFT_TPU_ONESHOT_TRANSPORT", "chain")
    t2 = _distributed()
    assert t2._tuning["hit"] is False  # different key -> re-measured
    monkeypatch.delenv("SPFFT_TPU_ONESHOT_TRANSPORT")
    assert _distributed()._tuning["hit"] is True  # original key still hits


def test_memory_store_when_env_unset(monkeypatch):
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t1 = _distributed()
    assert t1._tuning["wisdom_path"] is None
    n1 = _trial_count()
    t2 = _distributed()
    assert t2._tuning["hit"] is True
    assert _trial_count() == n1


def test_failed_candidate_is_isolated(tmp_path, monkeypatch):
    """One candidate failing (build/compile/run) must not abort plan
    construction: it becomes an ``error`` trial row and the winner comes
    from the measured rest."""
    from spfft_tpu.tuning import runner

    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    real = runner.measure_candidate

    def flaky(transform):
        if transform.exchange_type == ExchangeType.BUFFERED:
            raise RuntimeError("synthetic trial failure")
        return real(transform)

    monkeypatch.setattr(runner, "measure_candidate", flaky)
    t = _distributed()
    rec = t._tuning
    assert rec["provenance"] == "wisdom" and rec["hit"] is False
    assert t.exchange_type != ExchangeType.BUFFERED
    errors = [row for row in rec["trials"] if "error" in row]
    # the synthetic failure hits the whole BUFFERED family: the base
    # discipline and its OVERLAPPED chunk variants (tuning/candidates.py)
    assert {row["label"] for row in errors} == {
        "BUFFERED", "BUFFERED/ov2", "BUFFERED/ov4",
    }
    assert obs.validate_plan_card(t.report()) == []


def test_all_trials_failing_falls_back_to_model(monkeypatch):
    from spfft_tpu.tuning import runner

    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")

    def boom(transform):
        raise RuntimeError("synthetic trial failure")

    monkeypatch.setattr(runner, "measure_candidate", boom)
    t = _distributed()
    rec = t._tuning
    assert rec["provenance"] == "model"
    assert rec["reason"] == "all trial candidates failed"
    assert rec["trials"] and all("error" in row for row in rec["trials"])
    assert t.exchange_type == _distributed(policy="default").exchange_type


# ---- policy plumbing -------------------------------------------------------


def test_explicit_discipline_never_tuned(monkeypatch):
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t = _distributed(exchange_type=ExchangeType.BUFFERED)
    assert t._tuning is None
    assert t.exchange_type == ExchangeType.BUFFERED
    assert _trial_count() == 0
    assert "tuning" not in t.report()


def test_invalid_policy_rejected():
    with pytest.raises(InvalidParameterError):
        _distributed(policy="fastest")


def test_policy_env_knob(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_POLICY", "tuned")
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t = _distributed(policy=None)
    assert t._policy == "tuned"
    assert t._tuning is not None
    # explicit argument beats the env knob
    assert _distributed(policy="default")._policy == "default"


def test_wisdom_state_stamp(tmp_path, monkeypatch):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    t = _distributed()
    state = tuning.wisdom_state(t)
    assert state["configured"] is True
    assert state["path"] == str(tmp_path / "wisdom.json")
    assert state["policy"] == "tuned"
    assert state["provenance"] == "wisdom"
    assert state["hit"] is False
    untuned = tuning.wisdom_state(_distributed(policy="default"))
    assert untuned["provenance"] == "model" and untuned["hit"] is None


def test_trial_deadline_turns_hung_candidate_into_error_row(monkeypatch):
    """SPFFT_TPU_FENCE_BUDGET_S extends to whole tuning trials: a candidate
    that hangs (build or dispatch) fails typed TrialTimeout inside
    TRIAL_ERRORS and becomes an error row — tuned planning degrades to the
    model instead of stalling forever."""
    import time as _time

    from spfft_tpu.sync import FENCE_BUDGET_ENV
    from spfft_tpu.tuning import runner

    monkeypatch.setenv(FENCE_BUDGET_ENV, "0.05")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "0")
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    assert runner.trial_deadline_s() == pytest.approx(0.05 * 2)

    def build(cand):
        if cand["label"] == "hung":
            _time.sleep(5.0)  # a wedged compile/dispatch
        raise ValueError("fast candidate fails honestly")

    t0 = _time.perf_counter()
    rows = runner.run_trials(
        build, [{"label": "hung"}, {"label": "fast"}]
    )
    elapsed = _time.perf_counter() - t0
    assert elapsed < 2.0, "deadline did not bound the hung trial"
    by_label = {r["label"]: r for r in rows}
    assert "TrialTimeout" in by_label["hung"]["error"]
    assert "ValueError" in by_label["fast"]["error"]


def test_trial_deadline_unset_means_no_deadline(monkeypatch):
    from spfft_tpu.sync import FENCE_BUDGET_ENV
    from spfft_tpu.tuning import runner

    monkeypatch.delenv(FENCE_BUDGET_ENV, raising=False)
    assert runner.trial_deadline_s() == 0.0
    # and _run_deadlined with budget 0 runs inline
    assert runner._run_deadlined(lambda: 42, 0.0, "x") == 42


# ---- fleet bundles (wisdom merge/export) ------------------------------------


def _entry(key, choice, ms_list):
    trials = [{"label": f"c{i}", "ms": ms} for i, ms in enumerate(ms_list)]
    return tuning.make_entry(key, choice, trials)


def test_bundle_export_merge_best_measured_wins(tmp_path):
    a = tuning.WisdomStore(str(tmp_path / "a.json"))
    b = tuning.WisdomStore(str(tmp_path / "b.json"))
    k1, k2 = {"kind": "x", "n": 1}, {"kind": "x", "n": 2}
    a.record(k1, _entry(k1, {"w": "slow"}, [5.0]))
    b.record(k1, _entry(k1, {"w": "fast"}, [3.0, 9.0]))
    b.record(k2, _entry(k2, {"w": "only"}, [1.0]))
    bundle = tmp_path / "fleet.json"
    assert b.export(str(bundle)) == 2
    assert a.merge(str(bundle)) == (1, 1)  # k2 added, k1 replaced (3 < 5 ms)
    ent = a.entries()
    assert ent[tuning.key_digest(k1)]["choice"] == {"w": "fast"}
    assert ent[tuning.key_digest(k2)]["choice"] == {"w": "only"}
    # idempotent: re-merging the same bundle changes nothing
    assert a.merge(str(bundle)) == (0, 0)
    # losing direction: a's (now 3 ms) entry never regresses to 5 ms
    worse = tmp_path / "worse.json"
    assert a.export(str(worse)) == 2
    a.record(k1, _entry(k1, {"w": "fast"}, [2.0]))
    assert a.merge(str(worse)) == (0, 0)
    assert tuning.best_measured_ms(a.entries()[tuning.key_digest(k1)]) == 2.0


def test_bundle_measured_beats_unmeasured_and_malformed_skipped(tmp_path):
    a = tuning.WisdomStore(str(tmp_path / "a.json"))
    k = {"kind": "x", "n": 1}
    a.record(k, _entry(k, {"w": "model"}, []))  # unmeasured (model-derived)
    bundle = tmp_path / "fleet.json"
    doc = {
        "schema": tuning.WISDOM_SCHEMA,
        "entries": {
            tuning.key_digest(k): _entry(k, {"w": "measured"}, [4.0]),
            "malformed": {"choice": "not-a-dict"},
            "alsobad": ["nope"],
        },
    }
    bundle.write_text(json.dumps(doc))
    assert a.merge(str(bundle)) == (0, 1)  # measured beats unmeasured;
    # malformed rows are skipped, never displacing wisdom
    assert a.entries()[tuning.key_digest(k)]["choice"] == {"w": "measured"}


def test_bundle_schema_mismatch_raises_typed(tmp_path):
    a = tuning.WisdomStore(str(tmp_path / "a.json"))
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"schema": "bogus/9", "entries": {}}))
    with pytest.raises(InvalidParameterError, match="schema mismatch"):
        a.merge(str(bad))
    with pytest.raises(InvalidParameterError, match="unreadable"):
        a.merge(str(tmp_path / "missing.json"))


def test_bundle_corrupt_quarantine_parity(tmp_path):
    """A corrupt bundle gets exactly the store's corruption treatment —
    quarantined to *.corrupt, warned, counted — AND the merge fails loudly
    (typed), because a merge is an explicit operator action."""
    import warnings

    a = tuning.WisdomStore(str(tmp_path / "a.json"))
    k = {"kind": "x", "n": 1}
    a.record(k, _entry(k, {"w": "keep"}, [1.0]))
    corrupt = tmp_path / "fleet.json"
    corrupt.write_text("{ not json")
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with pytest.raises(InvalidParameterError, match="corrupt"):
            a.merge(str(corrupt))
    assert (tmp_path / "fleet.json.corrupt").exists()
    assert not corrupt.exists()
    assert any("quarantined" in str(w.message) for w in caught)
    counters = obs.snapshot()["counters"]
    assert counters.get("wisdom_quarantined_total", 0) >= 1, counters
    # the store itself is untouched
    assert a.entries()[tuning.key_digest(k)]["choice"] == {"w": "keep"}


def test_bundle_memory_store_parity(tmp_path):
    tuning.clear_memory()
    m = tuning.MemoryStore()
    k1, k2 = {"kind": "x", "n": 1}, {"kind": "x", "n": 2}
    m.record(k1, _entry(k1, {"w": "mem"}, []))
    bundle = tmp_path / "fleet.json"
    doc = {
        "schema": tuning.WISDOM_SCHEMA,
        "entries": {
            tuning.key_digest(k1): _entry(k1, {"w": "fleet"}, [2.0]),
            tuning.key_digest(k2): _entry(k2, {"w": "new"}, [1.0]),
        },
    }
    bundle.write_text(json.dumps(doc))
    assert m.merge(str(bundle)) == (1, 1)
    assert m.export(str(tmp_path / "out.json")) == 2
    out = json.loads((tmp_path / "out.json").read_text())
    assert out["schema"] == tuning.WISDOM_SCHEMA
    assert len(out["entries"]) == 2
