"""Guard: the pencil engines' pack/unpack must stay row-granular.

Round-4 on-chip finding (ROADMAP 8b): the pencil exchanges' pack/unpack ran as
(P, SG, Lz) ELEMENT scatters/gathers (~20 ns/element on TPU), making the
1x1-mesh pencil ~230x slower than the local engine at 256^3/15% — invisible on
the CPU mesh where pocketfft costs dominate, so every oracle test stayed green.
These tests make the regression visible off-chip: they lower the compiled MXU
pencil pipelines to StableHLO and assert no gather/scatter moves data
element-by-element. The detector itself lives in ``spfft_tpu.obs.hlo`` (it
was promoted into library code so plan cards report the same
``element_granular_ops`` signal these tests assert on). Reference pack/unpack
being matched: src/transpose/transpose_mpi_compact_buffered_host.cpp:109-175.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.obs.hlo import element_granular_ops as _element_granular_ops
from spfft_tpu.parameters import distribute_triplets
from utils import random_sparse_triplets, split_values


def _lowered_texts(p1, p2, exchange):
    import jax

    rng = np.random.default_rng(77)
    dx, dy, dz = 16, 16, 16
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, p1 * p2, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh2(p1, p2),
        exchange_type=exchange,
        engine="mxu",
    )
    assert t._engine == "pencil2-mxu"
    ex = t._exec
    pair = ex.pad_values(vps)
    texts = [ex._backward.lower(*pair, ex._value_indices).as_text()]
    # lowering only (no execution): the one-shot ragged transport lowers on
    # every backend but compiles only where the HLO is implemented.
    # eval_shape over the concrete arrays (jax.typeof is newer than the
    # oldest supported runtime; only shape/dtype are consumed anyway)
    out_shapes = jax.eval_shape(ex._backward_sm, *pair, ex._value_indices)
    texts.append(
        ex._forward[ScalingType.FULL]
        .lower(out_shapes[0], out_shapes[1], ex._value_indices)
        .as_text()
    )
    return texts


_DISCIPLINES = [
    ExchangeType.BUFFERED,
    ExchangeType.COMPACT_BUFFERED,  # RaggedBlockExchange rotation chain
    ExchangeType.UNBUFFERED,  # one-shot ragged-all-to-all (forced below)
]


@pytest.mark.parametrize("p1,p2", [(1, 1), (2, 2), (2, 4)])
@pytest.mark.parametrize("exchange", _DISCIPLINES)
def test_mxu_pencil_pipelines_have_no_element_scatters(
    p1, p2, exchange, monkeypatch
):
    if exchange == ExchangeType.UNBUFFERED:
        _require_ragged_a2a()
        # force the one-shot transport (the CPU probe would fall back to the
        # chain and hide OneShotBlockExchange from the guard)
        monkeypatch.setenv("SPFFT_TPU_ONESHOT_TRANSPORT", "ragged")
    for hlo in _lowered_texts(p1, p2, exchange):
        bad = _element_granular_ops(hlo)
        assert not bad, (
            "element-granular data movement in the compiled pencil pipeline "
            f"({exchange}; the round-4/5 on-chip pathology, ROADMAP 8b): {bad}"
        )


def _require_ragged_a2a():
    """Skip when the runtime predates the ragged-all-to-all HLO binding —
    forcing the one-shot transport cannot even lower there."""
    import jax

    if not hasattr(jax.lax, "ragged_all_to_all"):
        pytest.skip("jax.lax.ragged_all_to_all not available on this runtime")


def _lowered_1d_texts(exchange, monkeypatch):
    import jax

    if exchange == ExchangeType.UNBUFFERED:
        monkeypatch.setenv("SPFFT_TPU_ONESHOT_TRANSPORT", "ragged")
    rng = np.random.default_rng(78)
    dx, dy, dz = 16, 16, 16
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(4),
        exchange_type=exchange,
        engine="mxu",
    )
    ex = t._exec
    pair = ex.pad_values(vps)
    phase = ex._phase_args()
    texts = [ex._backward.lower(*pair, *phase).as_text()]
    out_shapes = jax.eval_shape(ex._backward_sm, *pair, *phase)
    texts.append(
        ex._forward[ScalingType.FULL]
        .lower(out_shapes[0], out_shapes[1], *phase)
        .as_text()
    )
    return texts


@pytest.mark.parametrize(
    "exchange", [ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED]
)
def test_mxu_1d_ragged_pipelines_have_no_element_scatters(exchange, monkeypatch):
    """The 1-D slab engines' ragged exchange paths (RaggedExchange chain /
    OneShotExchange) must stay row-granular too — the same pathology class
    fixed for the pencil exchanges this round (pod-relevant: single-chip
    P=1 plans specialize the exchange away, so only this lowering check sees
    it off-pod)."""
    if exchange == ExchangeType.UNBUFFERED:
        _require_ragged_a2a()
    for hlo in _lowered_1d_texts(exchange, monkeypatch):
        bad = _element_granular_ops(hlo)
        assert not bad, (
            "element-granular data movement in the compiled 1-D ragged "
            f"pipeline ({exchange}): {bad}"
        )
