"""Guard: the pencil engines' pack/unpack must stay row-granular.

Round-4 on-chip finding (ROADMAP 8b): the pencil exchanges' pack/unpack ran as
(P, SG, Lz) ELEMENT scatters/gathers (~20 ns/element on TPU), making the
1x1-mesh pencil ~230x slower than the local engine at 256^3/15% — invisible on
the CPU mesh where pocketfft costs dominate, so every oracle test stayed green.
These tests make the regression visible off-chip: they lower the compiled MXU
pencil pipelines to StableHLO and assert no gather/scatter moves data
element-by-element. Reference pack/unpack being matched:
src/transpose/transpose_mpi_compact_buffered_host.cpp:109-175.
"""
import re

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets
from utils import random_sparse_triplets, split_values

# metadata lookups (branch tables, shard geometry) legitimately gather single
# elements out of tiny operands; data arrays are far larger
_METADATA_ELEMS = 4096


def _operand_elems(shape_str: str) -> int:
    """Element count of a StableHLO tensor type like 'tensor<16385xf32>'."""
    dims = re.findall(r"(\d+)x", shape_str)
    n = 1
    for d in dims:
        n *= int(d)
    return n


def _element_granular_ops(hlo: str):
    """(op, operand, detail) rows for every gather/scatter that moves single
    elements out of/into a non-metadata operand."""
    bad = []
    # gathers: slice_sizes all-1 means one element per index row
    for m in re.finditer(
        r'"stablehlo\.gather"[^\n]*?slice_sizes\s*=\s*array<i64([^>]*)>[^\n]*?:\s*\(tensor<([^>]+)>',
        hlo,
    ):
        sizes = [int(x) for x in re.findall(r"-?\d+", m.group(1))]
        if sizes and all(s == 1 for s in sizes):
            if _operand_elems(m.group(2)) > _METADATA_ELEMS:
                bad.append(("gather", m.group(2), sizes))
    # scatters: no update_window_dims (StableHLO omits the attribute when
    # empty) means element updates
    for m in re.finditer(
        r'"stablehlo\.scatter"\(.*?\}\)\s*:\s*\(tensor<([^>]+)>', hlo, re.DOTALL
    ):
        mw = re.search(r"update_window_dims = \[([^\]]*)\]", m.group(0))
        window = re.findall(r"\d+", mw.group(1)) if mw else []
        if not window and _operand_elems(m.group(1)) > _METADATA_ELEMS:
            bad.append(("scatter", m.group(1), []))
    return bad


def _lowered_texts(p1, p2, exchange):
    import jax

    rng = np.random.default_rng(77)
    dx, dy, dz = 16, 16, 16
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, p1 * p2, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh2(p1, p2),
        exchange_type=exchange,
        engine="mxu",
    )
    assert t._engine == "pencil2-mxu"
    ex = t._exec
    pair = ex.pad_values(vps)
    texts = [ex._backward.lower(*pair, ex._value_indices).as_text()]
    # lowering only (no execution): the one-shot ragged transport lowers on
    # every backend but compiles only where the HLO is implemented
    out_shapes = jax.eval_shape(
        ex._backward_sm, *(jax.typeof(x) for x in (*pair, ex._value_indices))
    )
    texts.append(
        ex._forward[ScalingType.FULL]
        .lower(out_shapes[0], out_shapes[1], ex._value_indices)
        .as_text()
    )
    return texts


_DISCIPLINES = [
    ExchangeType.BUFFERED,
    ExchangeType.COMPACT_BUFFERED,  # RaggedBlockExchange rotation chain
    ExchangeType.UNBUFFERED,  # one-shot ragged-all-to-all (forced below)
]


@pytest.mark.parametrize("p1,p2", [(1, 1), (2, 2), (2, 4)])
@pytest.mark.parametrize("exchange", _DISCIPLINES)
def test_mxu_pencil_pipelines_have_no_element_scatters(
    p1, p2, exchange, monkeypatch
):
    if exchange == ExchangeType.UNBUFFERED:
        # force the one-shot transport (the CPU probe would fall back to the
        # chain and hide OneShotBlockExchange from the guard)
        monkeypatch.setenv("SPFFT_TPU_ONESHOT_TRANSPORT", "ragged")
    for hlo in _lowered_texts(p1, p2, exchange):
        bad = _element_granular_ops(hlo)
        assert not bad, (
            "element-granular data movement in the compiled pencil pipeline "
            f"({exchange}; the round-4/5 on-chip pathology, ROADMAP 8b): {bad}"
        )


def _lowered_1d_texts(exchange, monkeypatch):
    import jax

    if exchange == ExchangeType.UNBUFFERED:
        monkeypatch.setenv("SPFFT_TPU_ONESHOT_TRANSPORT", "ragged")
    rng = np.random.default_rng(78)
    dx, dy, dz = 16, 16, 16
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    per_shard = distribute_triplets(trip, 4, dy)
    vps = split_values(per_shard, trip, values)
    t = DistributedTransform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(4),
        exchange_type=exchange,
        engine="mxu",
    )
    ex = t._exec
    pair = ex.pad_values(vps)
    phase = ex._phase_args()
    texts = [ex._backward.lower(*pair, *phase).as_text()]
    out_shapes = jax.eval_shape(
        ex._backward_sm, *(jax.typeof(x) for x in (*pair, *phase))
    )
    texts.append(
        ex._forward[ScalingType.FULL]
        .lower(out_shapes[0], out_shapes[1], *phase)
        .as_text()
    )
    return texts


@pytest.mark.parametrize(
    "exchange", [ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED]
)
def test_mxu_1d_ragged_pipelines_have_no_element_scatters(exchange, monkeypatch):
    """The 1-D slab engines' ragged exchange paths (RaggedExchange chain /
    OneShotExchange) must stay row-granular too — the same pathology class
    fixed for the pencil exchanges this round (pod-relevant: single-chip
    P=1 plans specialize the exchange away, so only this lowering check sees
    it off-pod)."""
    for hlo in _lowered_1d_texts(exchange, monkeypatch):
        bad = _element_granular_ops(hlo)
        assert not bad, (
            "element-granular data movement in the compiled 1-D ragged "
            f"pipeline ({exchange}): {bad}"
        )
