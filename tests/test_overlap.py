"""OVERLAPPED exchange discipline: chunked double-buffered exchange parity.

The chunk count must never change results: overlapped plans (every chunk
count, both slab engines, the 2-D pencil path, C2C and R2C, f32 and f64,
padded and ``*_FLOAT`` wire formats) must agree with their bulk-synchronous
(``overlap=1``) twin and the local oracle. Seeding follows the
``SPFFT_TPU_FUZZ_SEED`` machinery of tests/test_engine_parity_fuzz.py: each
case prints its effective seed, so a failure replays exactly with
``SPFFT_TPU_FUZZ_SEED=<offset> pytest <nodeid>``.

Also pins the knob's behavior surface: the ragged disciplines ignore the
knob (their chains already round-pipeline), requests clamp to the chunkable
extent, the env knob and plan cards carry it, the perf layer scores
overlapped rows on exposed time while keeping exact wire bytes, and the
TUNED policy owns the knob end to end (candidates -> trials -> wisdom).
"""
import os

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    obs,
)
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.obs import perf
from spfft_tpu.parameters import distribute_triplets
from utils import assert_close, random_sparse_triplets

FUZZ_SEED = int(os.environ.get("SPFFT_TPU_FUZZ_SEED", "0"))


def fuzz_rng(base: int, case: int) -> np.random.Generator:
    seed = FUZZ_SEED + base + case
    print(f"fuzz seed = {seed} (SPFFT_TPU_FUZZ_SEED={FUZZ_SEED} + {base} + {case})")
    return np.random.default_rng(seed)


def _case_plan(rng, r2c, dtype, p_y=None):
    """Random dims/triplets/values for one parity case (hermitian-consistent
    values for R2C so forward(backward(v)) reproduces v)."""
    dx = int(rng.integers(5, 12))
    dy = int(rng.integers(6, 12) if p_y is None else rng.integers(p_y + 2, 12))
    dz = int(rng.integers(6, 13))
    trip = random_sparse_triplets(
        rng, dx, dy, dz, float(rng.uniform(0.4, 0.9)), hermitian=r2c
    )
    n = len(trip)
    if r2c:
        real = rng.standard_normal((dz, dy, dx))
        freq = np.fft.fftn(real) / (dx * dy * dz)
        values = freq[trip[:, 2], trip[:, 1], trip[:, 0]]
    else:
        values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    return (dx, dy, dz), trip, values.astype(
        np.complex64 if dtype == np.float32 else np.complex128
    )


def _shard(trip, values, shards, dy):
    per_shard = distribute_triplets(trip, shards, dy)
    lut = {tuple(t): v for t, v in zip(map(tuple, trip), values)}
    return per_shard, [
        np.asarray([lut[tuple(t)] for t in s]) for s in per_shard
    ]


def _roundtrip(t, vps):
    out = np.asarray(t.backward([v.copy() for v in vps]))
    back = np.concatenate(t.forward(scaling=ScalingType.FULL))
    return out, back


# ---- parity fuzz: overlapped vs unchunked ------------------------------------


@pytest.mark.parametrize("engine", ["xla", "mxu"])
@pytest.mark.parametrize("case", [0, 1, 2, 3])
def test_slab_overlap_parity(engine, case):
    """Chunk counts {2, 7, P} x {C2C, R2C} x {f32, f64} x padded/_FLOAT wire
    against the overlap=1 twin and the local oracle, per slab engine."""
    rng = fuzz_rng(7000, case)
    r2c = bool(case % 2)
    dtype = np.float64 if case // 2 % 2 else np.float32
    exchange = (
        ExchangeType.BUFFERED_FLOAT if dtype == np.float64 and case % 2 == 0
        else ExchangeType.BUFFERED
    )
    dims, trip, values = _case_plan(rng, r2c, dtype)
    dx, dy, dz = dims
    shards = int(rng.choice([2, 4]))
    per_shard, vps = _shard(trip, values, shards, dy)
    ttype = TransformType.R2C if r2c else TransformType.C2C
    tol = dict(dtype=np.float32) if dtype == np.float32 else {}

    local = Transform(
        ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip, dtype=dtype
    ).backward(values)

    ref = None
    for overlap in (1, 2, 7, shards):
        t = DistributedTransform(
            ProcessingUnit.HOST, ttype, dx, dy, dz,
            [p.copy() for p in per_shard],
            mesh=sp.make_fft_mesh(shards), dtype=dtype, engine=engine,
            exchange_type=exchange, overlap=overlap,
        )
        out, back = _roundtrip(t, vps)
        assert_close(out, local, **tol)
        if ref is None:
            ref = (out, back)
            assert t.overlap_chunks == 1
        else:
            # the chunked pipeline is the same arithmetic regrouped; parity
            # with the unchunked twin is exact on CPU
            np.testing.assert_allclose(out, ref[0], rtol=1e-6, atol=1e-8)
            np.testing.assert_allclose(back, ref[1], rtol=1e-6, atol=1e-8)


@pytest.mark.parametrize("engine", ["xla", "mxu"])
@pytest.mark.parametrize("case", [0, 1])
def test_pencil_overlap_parity(engine, case):
    """Chunked pencil pipelines (exchange A against y, exchange B against x)
    must match the bulk-synchronous twin and the local oracle."""
    rng = fuzz_rng(8000, 2 * case + (engine == "mxu"))
    r2c = bool(case % 2)
    dtype = np.float32 if case % 2 else np.float64
    p1, p2 = 2, 2
    dims, trip, values = _case_plan(rng, r2c, dtype, p_y=p1)
    dx, dy, dz = dims
    per_shard, vps = _shard(trip, values, p1 * p2, dy)
    ttype = TransformType.R2C if r2c else TransformType.C2C
    tol = dict(dtype=np.float32) if dtype == np.float32 else {}

    local = Transform(
        ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip, dtype=dtype
    ).backward(values)

    ref = None
    for overlap in (1, 2, 7):
        t = DistributedTransform(
            ProcessingUnit.HOST, ttype, dx, dy, dz,
            [p.copy() for p in per_shard],
            mesh=sp.make_fft_mesh2(p1, p2), dtype=dtype, engine=engine,
            exchange_type=ExchangeType.BUFFERED, overlap=overlap,
        )
        out, back = _roundtrip(t, vps)
        assert_close(out, local, **tol)
        if ref is None:
            ref = (out, back)
        else:
            assert 1 < t.overlap_chunks <= -(-dz // p2)
            np.testing.assert_allclose(out, ref[0], rtol=1e-6, atol=1e-8)
            np.testing.assert_allclose(back, ref[1], rtol=1e-6, atol=1e-8)


# ---- knob behavior -----------------------------------------------------------


def _small_dist(overlap=None, exchange=ExchangeType.BUFFERED, mesh=None,
                policy=None, **kw):
    trip = sp.create_spherical_cutoff_triplets(8, 8, 8, 0.9)
    return DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
        np.asarray(trip).copy(),
        mesh=mesh if mesh is not None else sp.make_fft_mesh(4),
        dtype=np.float32, engine="xla", exchange_type=exchange,
        overlap=overlap, policy=policy, **kw,
    )


def test_ragged_disciplines_ignore_overlap():
    """COMPACT/UNBUFFERED chains already pipeline in rounds — the knob
    clamps to 1 instead of building a second pipelining layer."""
    for exchange in (ExchangeType.COMPACT_BUFFERED, ExchangeType.UNBUFFERED):
        t = _small_dist(overlap=6, exchange=exchange)
        assert t.overlap_chunks == 1
        assert "overlapped" not in t._exec.exchange_transport()


def test_overlap_clamps_to_chunkable_extent():
    t = _small_dist(overlap=10_000)
    assert 1 < t.overlap_chunks <= t._exec._S
    assert t.exchange_rounds() == t.overlap_chunks
    assert t._exec.exchange_transport() == "chunked all_to_all"


def test_overlap_env_knob(monkeypatch):
    from spfft_tpu.parallel.policy import OVERLAP_ENV

    monkeypatch.setenv(OVERLAP_ENV, "3")
    t = _small_dist()  # overlap=None -> env
    assert t.overlap_chunks == 3
    monkeypatch.setenv(OVERLAP_ENV, "banana")
    with pytest.raises(InvalidParameterError):
        _small_dist()
    with pytest.raises(InvalidParameterError):
        _small_dist(overlap=0)


def test_plan_card_records_overlap_provenance():
    t = _small_dist(overlap=4)
    card = t.report()
    assert obs.validate_plan_card(card) == []
    assert card["exchange"]["overlap_chunks"] == t.overlap_chunks
    assert card["exchange"]["transport"] == "chunked all_to_all"
    assert card["execution"]["overlap_chunks"] == t.overlap_chunks
    policy = card["exchange_policy"]
    assert policy["chosen"] == f"BUFFERED/ov{t.overlap_chunks}"
    chosen = [a for a in policy["alternatives"] if a["chosen"]]
    assert len(chosen) == 1
    assert chosen[0]["discipline"] == policy["chosen"]
    assert chosen[0]["rounds"] == t.overlap_chunks
    # the overlapped row costs the same exact wire bytes as its padded base
    base = next(
        a for a in policy["alternatives"] if a["discipline"] == "BUFFERED"
    )
    assert chosen[0]["wire_bytes"] == base["wire_bytes"]


def test_grid_create_transform_threads_overlap():
    grid = sp.Grid(8, 8, 8, 64, ProcessingUnit.HOST, mesh=sp.make_fft_mesh(4))
    trip = sp.create_spherical_cutoff_triplets(8, 8, 8, 0.9)
    t = grid.create_transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        overlap=2,
    )
    assert t.overlap_chunks == 2
    local_grid = sp.Grid(8, 8, 8, 64, ProcessingUnit.HOST)
    with pytest.raises(InvalidParameterError):
        local_grid.create_transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
            overlap=2,
        )


# ---- perf accounting: exposed-time attribution -------------------------------


def test_perf_scores_overlap_on_exposed_time():
    """The overlapped report keeps the exact geometry wire bytes but
    attributes less time to the exchange — exchange_fraction is computed on
    the exposed (non-hidden) share."""
    reports = {}
    for overlap in (1, 4):
        t = _small_dist(overlap=overlap)
        seconds = 1e-3  # fixed wall time: attribution is deterministic
        reports[overlap] = perf.perf_report(t, seconds, repeats=1)
    for rep in reports.values():
        assert perf.validate_perf_report(rep) == []
    r1, r4 = reports[1], reports[4]
    names4 = {r["stage"] for r in r4["stages"]}
    assert "exchange overlapped" in names4
    assert "exchange" not in names4
    # modeled bytes equal the exact geometry wire volume under BOTH labels
    def wire(rep):
        return sum(
            r["bytes"] for r in rep["stages"]
            if r["stage"] in perf.EXCHANGE_STAGES
        )

    assert wire(r1) == wire(r4) == r1["wire_bytes_per_pair"]
    assert r4["overlap_chunks"] > 1 and r1["overlap_chunks"] == 1
    assert r4["exchange_fraction"] < r1["exchange_fraction"]
    # the overlapped row advertises what it hides behind
    (row,) = [r for r in r4["stages"] if r["stage"] == "exchange overlapped"]
    assert row["overlap"]["chunks"] == r4["overlap_chunks"]
    assert row["overlap"]["hides"] == "z transform"
    # stage seconds still sum to wall time by construction
    assert sum(r["seconds"] for r in r4["stages"]) == pytest.approx(1e-3)


def test_pencil_perf_overlap_rows():
    trip = sp.create_spherical_cutoff_triplets(8, 8, 8, 0.9)
    fr = {}
    for overlap in (1, 2):
        t = DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
            np.asarray(trip).copy(), mesh=sp.make_fft_mesh2(2, 4),
            dtype=np.float32, engine="xla",
            exchange_type=ExchangeType.BUFFERED, overlap=overlap,
        )
        rep = perf.perf_report(t, 1e-3, repeats=1)
        assert perf.validate_perf_report(rep) == []
        fr[overlap] = rep["exchange_fraction"]
        names = {r["stage"] for r in rep["stages"]}
        if overlap > 1:
            assert {"exchange A overlapped", "exchange B overlapped"} <= names
            rows = {
                r["stage"]: r for r in rep["stages"] if "overlapped" in r["stage"]
            }
            assert rows["exchange A overlapped"]["overlap"]["hides"] == "y transform"
            assert rows["exchange B overlapped"]["overlap"]["hides"] == "x transform"
        else:
            assert {"exchange A", "exchange B"} <= names
    assert fr[2] < fr[1]


# ---- tuner ownership ---------------------------------------------------------


def test_tuned_policy_owns_overlap_knob(tmp_path, monkeypatch):
    import spfft_tpu.tuning as tuning

    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    tuning.clear_memory()
    t = _small_dist(exchange=ExchangeType.DEFAULT, policy="tuned")
    rec = t._tuning
    labels = [r["label"] for r in rec["trials"]]
    assert any("/ov" in label for label in labels), labels
    assert "overlap" in rec["choice"]
    # overlapped trial rows are visible in the plan card's TUNED trial table
    card = t.report()
    assert any("/ov" in r["label"] for r in card["tuning"]["trials"])
    # wisdom hit reproduces discipline AND chunk count with zero trials
    t2 = _small_dist(exchange=ExchangeType.DEFAULT, policy="tuned")
    assert t2._tuning["hit"] is True
    assert t2.overlap_chunks == t.overlap_chunks
    assert t2.exchange_type == t.exchange_type
    # an explicit overlap pin removes the axis from the trial set
    tuning.clear_memory()
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom2.json"))
    t3 = _small_dist(exchange=ExchangeType.DEFAULT, policy="tuned", overlap=2)
    assert not any("/ov" in r["label"] for r in t3._tuning["trials"])


def test_overlap_candidates_shape():
    from spfft_tpu.tuning.candidates import (
        OVERLAP_CANDIDATE_CHUNKS,
        exchange_candidates,
    )

    cands = exchange_candidates([4, 4], [4, 4], one_shot_supported=False)
    ov_rows = [c for c in cands if "/ov" in c["label"]]
    assert {c["overlap"] for c in ov_rows} == set(OVERLAP_CANDIDATE_CHUNKS)
    assert all(c["exchange_type"] == "BUFFERED" for c in ov_rows)
    # model cost ranks overlapped rows behind plain BUFFERED (extra rounds,
    # same bytes): the measurement, not the model, decides if hiding wins
    base = next(c for c in cands if c["label"] == "BUFFERED")
    assert all(c["model_cost_bytes"] > base["model_cost_bytes"] for c in ov_rows)
    pinned = exchange_candidates([4, 4], [4, 4], one_shot_supported=False,
                                 overlap=3)
    assert not any("/ov" in c["label"] for c in pinned)
    assert all(c["overlap"] == 3 for c in pinned)
    pencil = exchange_candidates(pencil2=True)
    assert any("/ov" in c["label"] for c in pencil)
