"""Per-object device binding.

Reference parity: each Grid/Transform is pinned to the device current at its
creation (reference: src/spfft/grid_internal.cpp:82,
docs/source/details.rst:104-106 — "one device per Grid/Transform"), so
independent local plans can occupy different chips of a slice. Here the
binding is the ``device=`` ctor kwarg (or ``jax.default_device`` at creation);
the virtual 8-device CPU backend stands in for multiple chips.
"""
import numpy as np

import jax

import spfft_tpu as sp
from utils import random_sparse_triplets


def _plan_on(device, dim=12, seed=0):
    rng = np.random.default_rng(seed)
    trip = random_sparse_triplets(rng, dim, dim, dim, 0.5)
    t = sp.Transform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, dim, dim, dim,
        indices=trip, dtype=np.float64, device=device,
    )
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    return t, trip, v


def _dense_oracle(trip, v, dim):
    dense = np.zeros((dim,) * 3, dtype=np.complex128)
    dense[trip[:, 2], trip[:, 1], trip[:, 0]] = v
    return np.fft.ifftn(dense) * dim**3


def test_two_plans_on_two_devices_run_concurrently():
    devs = jax.devices("cpu")
    assert len(devs) >= 2
    t0, trip0, v0 = _plan_on(devs[0], seed=1)
    t1, trip1, v1 = _plan_on(devs[1], seed=2)
    assert t0.device == devs[0]
    assert t1.device == devs[1]
    assert t0.device_id != t1.device_id
    # dispatch both before either result is awaited (async exec mode), then
    # synchronize and check both against the dense oracle
    t0.set_execution_mode(sp.ExecType.ASYNCHRONOUS)
    t1.set_execution_mode(sp.ExecType.ASYNCHRONOUS)
    s0 = t0.backward(v0)
    s1 = t1.backward(v1)
    t0.synchronize()
    t1.synchronize()
    np.testing.assert_allclose(s0, _dense_oracle(trip0, v0, 12), atol=1e-9)
    np.testing.assert_allclose(s1, _dense_oracle(trip1, v1, 12), atol=1e-9)


def test_results_are_committed_to_the_bound_device():
    dev = jax.devices("cpu")[3]
    t, trip, v = _plan_on(dev, seed=3)
    t.backward(v)
    pair = t.space_domain_data(sp.ProcessingUnit.GPU)
    arrs = pair if isinstance(pair, tuple) else (pair,)
    for a in arrs:
        assert list(a.devices()) == [dev]


def test_grid_device_flows_to_transforms():
    dev = jax.devices("cpu")[2]
    grid = sp.Grid(16, 16, 16, 16 * 16, sp.ProcessingUnit.HOST, device=dev)
    assert grid.device == dev
    rng = np.random.default_rng(4)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.6)
    t = grid.create_transform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, 8, 8, 8,
        indices=trip, dtype=np.float64,
    )
    assert t.device == dev
    # clone inherits the binding (reference: clone keeps the device)
    assert t.clone().device == dev


def test_default_device_at_creation_is_honored():
    devs = jax.devices("cpu")
    rng = np.random.default_rng(5)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.6)
    with jax.default_device(devs[5]):
        t = sp.Transform(
            sp.ProcessingUnit.HOST, sp.TransformType.C2C, 8, 8, 8,
            indices=trip, dtype=np.float64,
        )
    assert t.device == devs[5]
    # creation-time binding sticks after the context exits
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    t.backward(v)
    pair = t.space_domain_data(sp.ProcessingUnit.GPU)
    arrs = pair if isinstance(pair, tuple) else (pair,)
    for a in arrs:
        assert list(a.devices()) == [devs[5]]
