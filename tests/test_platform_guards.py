"""Guarded platform resolution and hang protection (spfft_tpu/_platform.py).

These guards exist because initializing an unreachable accelerator plugin can
block a process forever (the reference's HOST paths never touch a GPU
runtime; ours must match — see _platform.py's module docstring). CPU-forced
subprocesses validate the behaviors without any accelerator.
"""
import os
import subprocess
import sys
import time
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent


def _run(code, timeout=120, env_extra=None):
    env = {**os.environ, "JAX_PLATFORMS": "", "PYTHONPATH": str(ROOT)}
    env.pop("XLA_FLAGS", None)
    env.update(env_extra or {})
    return subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=timeout,
        env=env,
    )


def test_hang_watchdog_fires_fast_and_exits_with_code():
    """A blocked body must become a fast nonzero exit with a stack dump, not
    a driver timeout (round-2's MULTICHIP rc=124 failure mode)."""
    t0 = time.monotonic()
    r = _run(
        "import time\n"
        "from spfft_tpu._platform import hang_watchdog\n"
        "hang_watchdog('t', 'T_BUDGET', 2, exit_code=7)\n"
        "time.sleep(60)\n",
        timeout=50,
    )
    elapsed = time.monotonic() - t0
    assert r.returncode == 7, r.stderr[-500:]
    assert elapsed < 30
    assert "exceeded 2s wall-clock budget" in r.stderr
    assert "Current thread" in r.stderr  # faulthandler stack dump


def test_hang_watchdog_disarm_prevents_exit():
    r = _run(
        "import time\n"
        "from spfft_tpu._platform import hang_watchdog\n"
        "disarm = hang_watchdog('t', 'T_BUDGET', 1, exit_code=7)\n"
        "disarm()\n"
        "time.sleep(2)\n"
        "print('survived')\n",
    )
    assert r.returncode == 0, r.stderr[-500:]
    assert "survived" in r.stdout


def test_hang_watchdog_budget_env_override():
    t0 = time.monotonic()
    r = _run(
        "import time\n"
        "from spfft_tpu._platform import hang_watchdog\n"
        "hang_watchdog('t', 'T_BUDGET', 300, exit_code=5)\n"
        "time.sleep(60)\n",
        timeout=50,
        env_extra={"T_BUDGET": "2"},
    )
    assert r.returncode == 5
    assert time.monotonic() - t0 < 30


def test_cpu_devices_rebuilds_on_virtual_count_change():
    """The private-client cache keys on jax_num_cpu_devices: a later
    configure_virtual_devices must not be silently ignored (round-3 review
    finding)."""
    r = _run(
        "import jax\n"
        "from spfft_tpu._platform import cpu_devices\n"
        "assert len(cpu_devices()) >= 1\n"
        "try:\n"
        "    jax.config.update('jax_num_cpu_devices', 6)\n"
        "except AttributeError:\n"
        # jax < 0.4.38 has no late-rebind knob (XLA_FLAGS at client creation
        # is the only control there) — nothing to guard
        "    print('skip: no jax_num_cpu_devices on this runtime')\n"
        "    raise SystemExit(0)\n"
        "assert len(cpu_devices()) == 6, cpu_devices()\n"
        "print('ok')\n",
        # non-cpu-only platform config forces the private-client path
        env_extra={"JAX_PLATFORMS": ""},
    )
    assert r.returncode == 0, r.stderr[-800:]
    if "skip:" in r.stdout:
        pytest.skip("jax_num_cpu_devices not available on this runtime")
    assert "ok" in r.stdout
