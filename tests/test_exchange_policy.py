"""ExchangeType.DEFAULT auto-policy (parallel/policy.py).

The reference hardwires DEFAULT to COMPACT_BUFFERED
(reference: src/spfft/grid_internal.cpp:176-179); here DEFAULT resolves by a
cost model over the plan's exact wire volumes, round counts, and backend
collective support. These tests pin the policy's decisions on the measured
geometry classes of BASELINE.md's discipline tables, verify its volume
accounting agrees with the engines', and check end-to-end resolution through
DistributedTransform.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu.parallel.policy import (
    discipline_volumes,
    resolve_default_exchange,
)
from spfft_tpu.types import ExchangeType
from utils import random_sparse_triplets


def test_balanced_plan_picks_buffered():
    # Balanced sticks and planes: COMPACT/UNBUFFERED tie or barely undercut
    # the padded volume, so the single fused all_to_all wins on rounds
    # (BASELINE.md: balanced rows at P in {8, 16, 32}).
    n = [40, 40, 40, 40]
    l = [8, 8, 8, 8]
    assert (
        resolve_default_exchange(n, l, one_shot_supported=True)
        == ExchangeType.BUFFERED
    )
    assert (
        resolve_default_exchange(n, l, one_shot_supported=False)
        == ExchangeType.BUFFERED
    )


def test_single_shard_picks_buffered():
    assert (
        resolve_default_exchange([100], [32], one_shot_supported=True)
        == ExchangeType.BUFFERED
    )


def test_imbalanced_plan_with_one_shot_picks_unbuffered():
    # Strong stick imbalance at a payload size where the saved bytes dwarf
    # one round's cost: the exact one-shot exchange wins (the imbalanced rows
    # of BASELINE.md's table, on the TPU transport).
    n = [4000, 8000, 4000, 8000]
    l = [64, 64, 64, 64]
    vols = discipline_volumes(n, l)
    assert vols[ExchangeType.UNBUFFERED] < vols[ExchangeType.BUFFERED]
    assert (
        resolve_default_exchange(n, l, one_shot_supported=True)
        == ExchangeType.UNBUFFERED
    )


def test_imbalanced_plan_without_one_shot_weighs_rounds():
    # Without the one-shot transport, exact-counts disciplines ride the
    # chain, whose round-5 row-granular 2-D windows tie the padded volume
    # (every step faces a max shard on each dim) — so with P-1 rounds the
    # chain always loses to BUFFERED's single collective when one-shot is
    # unavailable, at any imbalance or payload size.
    for n, l in (
        ([4000, 8000, 4000, 8000], [64, 64, 64, 64]),
        ([8000, 1000, 8000, 1000], [16, 128, 16, 128]),
        ([4, 8, 4, 8], [2, 2, 2, 2]),
    ):
        assert (
            resolve_default_exchange(n, l, one_shot_supported=False)
            == ExchangeType.BUFFERED
        )


def test_stick_imbalance_oneshot_undercuts_padded():
    # Stick imbalance: UNBUFFERED's exact rows (x the full L_max width)
    # undercut the padded volume; the row-granular COMPACT chain's windows
    # tie it (round-5 transport — the chain's value is portability, the
    # byte savings live in the one-shot form).
    n = [8000, 1000, 8000, 1000]
    l = [16, 128, 16, 128]
    vols = discipline_volumes(n, l)
    assert (
        vols[ExchangeType.UNBUFFERED]
        < vols[ExchangeType.COMPACT_BUFFERED]
        == vols[ExchangeType.BUFFERED]
    )


def test_round_cost_env_override(monkeypatch):
    # A huge per-round cost forces the single-round disciplines.
    n = [4000, 8000, 4000, 8000]
    l = [64, 64, 64, 64]
    monkeypatch.setenv("SPFFT_TPU_EXCH_ROUND_COST_KB", str(1 << 30))
    assert (
        resolve_default_exchange(n, l, one_shot_supported=False)
        == ExchangeType.BUFFERED
    )
    assert (
        resolve_default_exchange(n, l, one_shot_supported=True)
        == ExchangeType.UNBUFFERED
    )


@pytest.mark.parametrize("discipline", [
    ExchangeType.BUFFERED,
    ExchangeType.COMPACT_BUFFERED,
    ExchangeType.UNBUFFERED,
])
def test_volumes_match_engine_accounting(discipline):
    """discipline_volumes agrees with the engines' exchange_wire_bytes."""
    from spfft_tpu.parallel.mesh import make_fft_mesh

    rng = np.random.default_rng(3)
    dims = (12, 10, 16)
    trip = random_sparse_triplets(rng, *dims, 0.4)
    mesh = make_fft_mesh(4)
    t = sp.DistributedTransform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, *dims,
        trip, mesh=mesh, exchange_type=discipline, dtype=np.float32,
    )
    p = t._params
    vols = discipline_volumes(p.num_sticks_per_shard, p.local_z_lengths)
    assert t.exchange_wire_bytes() == vols[discipline] * 2 * 4


def test_pencil2_wire_volume_vs_slab(monkeypatch):
    """The 2-D pencil's exchange volume stays within 1.5x the 1-D slab's.

    Column-local stick placement (distribute_triplets layout=...) plus the
    ownership-aligned x-grouping make pencil exchange A column-diagonal, so
    with the one-shot exact transport only (P2-1)/P2 of the stick data plus
    the structural dense exchange B crosses the wire (VERDICT r3 item 4; the
    round-3 engine measured 2.7x here)."""
    from spfft_tpu.parallel.mesh import make_fft_mesh, make_fft_mesh2

    dim, nx = 64, 10  # benchmark x-slab stick model, scaled down
    xs, ys, zs = np.meshgrid(
        np.arange(nx), np.arange(dim), np.arange(dim), indexing="ij"
    )
    trip = np.stack([xs.ravel(), ys.ravel(), zs.ravel()], 1).astype(np.int32)
    t1 = sp.DistributedTransform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, dim, dim, dim, trip,
        mesh=make_fft_mesh(4), exchange_type=ExchangeType.BUFFERED,
        dtype=np.float32,
    )
    monkeypatch.setenv("SPFFT_TPU_ONESHOT_TRANSPORT", "ragged")
    t2 = sp.DistributedTransform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, dim, dim, dim, trip,
        mesh=make_fft_mesh2(2, 2), dtype=np.float32,
    )
    assert t2.exchange_type == ExchangeType.UNBUFFERED
    assert t2.exchange_wire_bytes() <= 1.5 * t1.exchange_wire_bytes()
    assert t2.exchange_rounds() == 2


@pytest.mark.parametrize("seed", [11, 12, 13, 14, 15, 16])
def test_default_pick_minimizes_engine_accounted_cost(seed):
    """Property: on randomized stick/plane distributions, the discipline
    DEFAULT picks has minimal ENGINE-accounted cost (exchange_wire_bytes +
    exchange_rounds x round_cost) among all three disciplines as actually
    instantiated — so the policy's internal volume model can never silently
    diverge from what the engines put on the wire (VERDICT r4 item 6)."""
    from spfft_tpu.parallel.mesh import make_fft_mesh
    from spfft_tpu.parallel.policy import round_cost_bytes

    rng = np.random.default_rng(seed)
    dims = (14, 12, 16)
    trip = random_sparse_triplets(rng, *dims, 0.5)
    P = 4
    weights = rng.integers(1, 10, P)
    from spfft_tpu.parameters import distribute_triplets

    per_shard = distribute_triplets(trip, P, dims[1], weights=list(weights))
    mesh = make_fft_mesh(P)

    def cost_of(t):
        return t.exchange_wire_bytes() + t.exchange_rounds() * round_cost_bytes()

    t_def = sp.DistributedTransform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, *dims,
        [p.copy() for p in per_shard], mesh=mesh, dtype=np.float32,
        engine="xla",
    )
    assert t_def.exchange_type != ExchangeType.DEFAULT
    costs = {}
    for d in (
        ExchangeType.BUFFERED,
        ExchangeType.COMPACT_BUFFERED,
        ExchangeType.UNBUFFERED,
    ):
        t = sp.DistributedTransform(
            sp.ProcessingUnit.HOST, sp.TransformType.C2C, *dims,
            [p.copy() for p in per_shard], mesh=mesh, dtype=np.float32,
            exchange_type=d, engine="xla",
        )
        costs[d] = cost_of(t)
    # minimal cost, not a specific name: ties may resolve either way
    assert costs[t_def.exchange_type] == min(costs.values()), (
        t_def.exchange_type,
        costs,
    )


@pytest.mark.parametrize("seed,p1,p2", [(21, 2, 2), (22, 4, 2), (23, 2, 4)])
def test_pencil2_default_pick_minimizes_accounted_cost(seed, p1, p2):
    """Same property for the 2-D pencil engine's in-plan DEFAULT resolution
    (its own two-exchange cost model, pencil2._resolve_pencil2_default)."""
    from spfft_tpu.parallel.mesh import make_fft_mesh2
    from spfft_tpu.parallel.policy import round_cost_bytes
    from spfft_tpu.parameters import distribute_triplets

    rng = np.random.default_rng(seed)
    dims = (12, 10, 14)
    trip = random_sparse_triplets(rng, *dims, 0.5)
    P = p1 * p2
    weights = rng.integers(1, 8, P)
    per_shard = distribute_triplets(trip, P, dims[1], weights=list(weights))
    mesh = make_fft_mesh2(p1, p2)

    def cost_of(t):
        return t.exchange_wire_bytes() + t.exchange_rounds() * round_cost_bytes()

    t_def = sp.DistributedTransform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, *dims,
        [p.copy() for p in per_shard], mesh=mesh, dtype=np.float32,
        engine="xla",
    )
    assert t_def.exchange_type != ExchangeType.DEFAULT
    costs = {}
    for d in (
        ExchangeType.BUFFERED,
        ExchangeType.COMPACT_BUFFERED,
        ExchangeType.UNBUFFERED,
    ):
        t = sp.DistributedTransform(
            sp.ProcessingUnit.HOST, sp.TransformType.C2C, *dims,
            [p.copy() for p in per_shard], mesh=mesh, dtype=np.float32,
            exchange_type=d, engine="xla",
        )
        costs[d] = cost_of(t)
    assert costs[t_def.exchange_type] == min(costs.values()), (
        t_def.exchange_type,
        costs,
    )


def test_default_resolves_to_concrete_discipline():
    from spfft_tpu.parallel.mesh import make_fft_mesh

    rng = np.random.default_rng(5)
    dims = (12, 10, 16)
    trip = random_sparse_triplets(rng, *dims, 0.4)
    mesh = make_fft_mesh(4)
    t = sp.DistributedTransform(
        sp.ProcessingUnit.HOST, sp.TransformType.C2C, *dims,
        trip, mesh=mesh, dtype=np.float32,
    )
    assert t.exchange_type != ExchangeType.DEFAULT
    # balanced distribute_triplets layout -> the fused padded collective
    assert t.exchange_type == ExchangeType.BUFFERED
    # and the resolved plan still round-trips
    v = (
        rng.standard_normal(t.num_global_elements)
        + 1j * rng.standard_normal(t.num_global_elements)
    ).astype(np.complex64)
    per = np.split(v, np.cumsum(
        [t.num_local_elements(r) for r in range(4)])[:-1])
    space = t.backward(per)
    out = t.forward(space, scaling=sp.ScalingType.FULL)
    np.testing.assert_allclose(
        np.concatenate(out), v, rtol=0, atol=2e-5
    )
