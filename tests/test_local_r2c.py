"""Local R2C/C2R transform tests.

Covers hermitian-symmetry completion: full half-spectrum round trips, omission of
redundant x=0-plane sticks and (0,0)-stick entries (reference:
docs/source/details.rst:31-40), and sparse stick subsets against a hermitian-extension
oracle.
"""
import numpy as np
import pytest

from spfft_tpu import ProcessingUnit, ScalingType, Transform, TransformType
from utils import assert_close, random_sparse_triplets, storage

DIMS = [(4, 4, 4), (6, 5, 4), (11, 12, 13), (16, 16, 16)]


def full_half_triplets(dx, dy, dz):
    xs = np.arange(dx // 2 + 1)
    g = np.stack(np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1)
    return g.reshape(-1, 3)


def nonredundant_triplets(dx, dy, dz):
    """Half spectrum minus the redundant parts: for x=0 keep only y in [0, dy//2];
    for (x=0, y=0) keep only z in [0, dz//2]."""
    out = []
    for x in range(dx // 2 + 1):
        for y in range(dy):
            if x == 0 and y > dy // 2:
                continue
            for z in range(dz):
                if x == 0 and y == 0 and z > dz // 2:
                    continue
                out.append((x, y, z))
    return np.asarray(out)


def make(dims, triplets, dtype=np.float64):
    return Transform(
        ProcessingUnit.HOST,
        TransformType.R2C,
        dims[0],
        dims[1],
        dims[2],
        indices=triplets,
        dtype=dtype,
    )


@pytest.mark.parametrize("dims", DIMS)
def test_r2c_roundtrip_full_half_spectrum(dims):
    rng = np.random.default_rng(21)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    t = make(dims, full_half_triplets(dx, dy, dz))
    values = t.forward(r, scaling=ScalingType.FULL)
    out = np.asarray(t.backward(values))
    assert out.dtype == np.float64
    assert_close(out, r)
    # run twice (zeroing check)
    assert_close(np.asarray(t.backward(values)), r)


@pytest.mark.parametrize("dims", DIMS)
def test_r2c_redundant_values_omitted(dims):
    """Only non-redundant frequencies provided; symmetry completion must reconstruct
    the full real field."""
    rng = np.random.default_rng(22)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)

    trip = nonredundant_triplets(dx, dy, dz)
    xs, ys, zs = trip[:, 0], trip[:, 1], trip[:, 2]
    values = freq[zs, ys, xs]

    t = make(dims, trip)
    out = np.asarray(t.backward(values))
    assert_close(out, r)


@pytest.mark.parametrize("dims", DIMS)
def test_r2c_forward_vs_oracle(dims):
    rng = np.random.default_rng(23)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.6, hermitian=True)
    xs, ys, zs = (storage(trip[:, i], d) for i, d in ((0, dx), (1, dy), (2, dz)))

    t = make(dims, trip)
    out = np.asarray(t.forward(r))
    expected = np.fft.fftn(r)[zs, ys, xs]
    assert_close(out, expected)


@pytest.mark.parametrize("dims", DIMS)
def test_r2c_sparse_backward_vs_hermitian_extension_oracle(dims):
    """Backward of a sparse hermitian stick subset == dense inverse DFT of the
    hermitian-closed masked spectrum."""
    rng = np.random.default_rng(24)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx))
    full = np.fft.fftn(r)

    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5, hermitian=True)
    # Hermitian completion is only defined for the x=0 plane (reference:
    # docs/source/details.rst:37-40); on the x-Nyquist plane (even dx) a stick's
    # mirror (hx, -y) must be supplied by the caller. Drop unpaired Nyquist sticks.
    if dx % 2 == 0:
        hx = dx // 2
        stick_set = {(int(t[0]), int(t[1]) % dy) for t in trip}
        keep = [
            i
            for i, t in enumerate(trip)
            if t[0] != hx or (hx, (-int(t[1])) % dy) in stick_set
        ]
        trip = trip[keep]
    xs, ys, zs = (
        np.asarray(storage(trip[:, i], d)) for i, d in ((0, dx), (1, dy), (2, dz))
    )
    values = full[zs, ys, xs]

    # hermitian-closed masked spectrum
    dense = np.zeros((dz, dy, dx), dtype=np.complex128)
    dense[zs, ys, xs] = values
    dense[(-zs) % dz, (-ys) % dy, (-xs) % dx] = np.conj(values)
    expected = np.fft.ifftn(dense) * (dx * dy * dz)
    assert np.abs(expected.imag).max() < 1e-9
    expected = expected.real

    t = make(dims, trip)
    out = np.asarray(t.backward(values))
    assert_close(out, expected)


def test_r2c_float32():
    rng = np.random.default_rng(25)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    r = rng.standard_normal((dz, dy, dx)).astype(np.float32)
    t = make(dims, full_half_triplets(dx, dy, dz), dtype=np.float32)
    values = t.forward(r, scaling=ScalingType.FULL)
    out = np.asarray(t.backward(values))
    assert out.dtype == np.float32
    assert_close(out, r, dtype=np.float32)
