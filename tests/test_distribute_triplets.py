"""Properties of the whole-stick partitioner (reference zStickDistribution
weight semantics, tests/test_util/generate_indices.hpp:39-100).
"""
import numpy as np
import pytest

from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.parameters import distribute_triplets, stick_keys
from utils import random_sparse_triplets


def _whole_sticks(per_shard, dy):
    seen = {}
    for r, part in enumerate(per_shard):
        for k in np.unique(stick_keys(part, dy)) if len(part) else []:
            assert k not in seen, f"stick {k} split across shards {seen.get(k)} and {r}"
            seen[k] = r
    return seen


def test_value_conservation_and_whole_sticks():
    rng = np.random.default_rng(0)
    trip = random_sparse_triplets(rng, 12, 13, 14, 0.6, z_fill=0.7)
    per_shard = distribute_triplets(trip, 5, 13)
    assert sum(len(p) for p in per_shard) == len(trip)
    _whole_sticks(per_shard, 13)
    # reasonable balance: no shard more than 2x the mean value count
    counts = np.array([len(p) for p in per_shard])
    assert counts.max() <= 2 * counts.mean()


def test_zero_weight_shard_receives_nothing():
    rng = np.random.default_rng(1)
    trip = random_sparse_triplets(rng, 8, 9, 10, 0.7)
    per_shard = distribute_triplets(trip, 3, 9, weights=[1.0, 0.0, 1.0])
    assert len(per_shard[1]) == 0
    assert sum(len(p) for p in per_shard) == len(trip)


def test_weighted_split_skews_load():
    rng = np.random.default_rng(2)
    trip = random_sparse_triplets(rng, 16, 16, 16, 0.8)
    per_shard = distribute_triplets(trip, 2, 16, weights=[3.0, 1.0])
    # shard 0 should carry roughly 3x shard 1 (within whole-stick granularity)
    assert len(per_shard[0]) > 2 * len(per_shard[1])


@pytest.mark.parametrize(
    "bad", [[1.0], [-1.0, 2.0, 1.0], [0.0, 0.0, 0.0]]
)
def test_invalid_weights_rejected(bad):
    rng = np.random.default_rng(3)
    trip = random_sparse_triplets(rng, 6, 6, 6, 0.5)
    with pytest.raises(InvalidParameterError):
        distribute_triplets(trip, 3, 6, weights=bad)


def test_zero_shards_rejected():
    with pytest.raises(InvalidParameterError):
        distribute_triplets(np.zeros((0, 3), dtype=np.int64), 0, 4)
