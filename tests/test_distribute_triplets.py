"""Properties of the whole-stick partitioner (reference zStickDistribution
weight semantics, tests/test_util/generate_indices.hpp:39-100).
"""
import numpy as np
import pytest

from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.parameters import distribute_triplets, stick_keys
from utils import random_sparse_triplets


def _whole_sticks(per_shard, dy):
    seen = {}
    for r, part in enumerate(per_shard):
        for k in np.unique(stick_keys(part, dy)) if len(part) else []:
            assert k not in seen, f"stick {k} split across shards {seen.get(k)} and {r}"
            seen[k] = r
    return seen


def test_value_conservation_and_whole_sticks():
    rng = np.random.default_rng(0)
    trip = random_sparse_triplets(rng, 12, 13, 14, 0.6, z_fill=0.7)
    per_shard = distribute_triplets(trip, 5, 13)
    assert sum(len(p) for p in per_shard) == len(trip)
    _whole_sticks(per_shard, 13)
    # reasonable balance: no shard more than 2x the mean value count
    counts = np.array([len(p) for p in per_shard])
    assert counts.max() <= 2 * counts.mean()


def test_zero_weight_shard_receives_nothing():
    rng = np.random.default_rng(1)
    trip = random_sparse_triplets(rng, 8, 9, 10, 0.7)
    per_shard = distribute_triplets(trip, 3, 9, weights=[1.0, 0.0, 1.0])
    assert len(per_shard[1]) == 0
    assert sum(len(p) for p in per_shard) == len(trip)


def test_weighted_split_skews_load():
    rng = np.random.default_rng(2)
    trip = random_sparse_triplets(rng, 16, 16, 16, 0.8)
    per_shard = distribute_triplets(trip, 2, 16, weights=[3.0, 1.0])
    # shard 0 should carry roughly 3x shard 1 (within whole-stick granularity)
    assert len(per_shard[0]) > 2 * len(per_shard[1])


@pytest.mark.parametrize(
    "bad", [[1.0], [-1.0, 2.0, 1.0], [0.0, 0.0, 0.0]]
)
def test_invalid_weights_rejected(bad):
    rng = np.random.default_rng(3)
    trip = random_sparse_triplets(rng, 6, 6, 6, 0.5)
    with pytest.raises(InvalidParameterError):
        distribute_triplets(trip, 3, 6, weights=bad)


def test_zero_shards_rejected():
    with pytest.raises(InvalidParameterError):
        distribute_triplets(np.zeros((0, 3), dtype=np.int64), 0, 4)


def test_layout_mode_column_local_and_centered():
    """layout=(P1, P2): whole sticks, column-local x (every stick of column
    group a lands on a shard of column a), value conservation — including
    with CENTERED indices, where the storage x of a negative caller x folds
    onto the same physical column (the rint key-recovery path)."""
    import spfft_tpu as sp

    dx = dy = dz = 16
    # centered spherical set: caller x spans negatives
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, 0.8)
    assert (trip[:, 0] < 0).any(), "test needs centered indices"
    P1, P2 = 2, 2
    per = distribute_triplets(trip, P1 * P2, dy, layout=(P1, P2), dim_x=dx)
    # value conservation + whole sticks
    assert sum(len(p) for p in per) == len(trip)
    _whole_sticks(per, dy)
    # column-locality in STORAGE x: each physical x column appears on the
    # shards of exactly one column group
    col_of_x = {}
    for r, part in enumerate(per):
        col = r // P2
        xs = np.where(part[:, 0] < 0, part[:, 0] + dx, part[:, 0])
        for x in np.unique(xs):
            assert col_of_x.setdefault(int(x), col) == col, (
                f"storage x={x} split across column groups"
            )
    # balanced-ish: no column group empty
    assert len({c for c in col_of_x.values()}) == P1


def test_layout_mode_validation():
    t = random_sparse_triplets(np.random.default_rng(0), 8, 8, 8, 0.5)
    with pytest.raises(InvalidParameterError):
        distribute_triplets(t, 4, 8, layout=(3, 2), dim_x=8)  # 3*2 != 4
    with pytest.raises(InvalidParameterError):
        distribute_triplets(t, 4, 8, layout=(2, 2))  # dim_x required
    with pytest.raises(InvalidParameterError):
        distribute_triplets(t, 4, 8, weights=[1, 1, 1, 1], layout=(2, 2), dim_x=8)


def test_layout_mode_dominant_column_rebalance():
    """A value-dominant x column must not starve the other column groups of
    ALL their sticks (advisor r4): when count-quantile snapping would leave a
    group empty, the split falls back to even column boundaries — whole
    columns stay together and every group owns at least one column whenever
    P1 <= #columns."""
    trip = [(0, y % 8, z) for y in range(8) for z in range(125)]
    trip += [(x, 0, z) for x in (1, 2, 3) for z in (0, 1)]
    trip = np.asarray(trip, dtype=np.int64)
    P1, P2 = 4, 2
    per = distribute_triplets(trip, P1 * P2, 8, layout=(P1, P2), dim_x=4)
    group_sizes = [
        sum(len(per[a * P2 + b]) for b in range(P2)) for a in range(P1)
    ]
    assert all(g > 0 for g in group_sizes), group_sizes
    # column-locality still holds
    col_of_x = {}
    for r, part in enumerate(per):
        for x in np.unique(part[:, 0]) if len(part) else []:
            assert col_of_x.setdefault(int(x), r // P2) == r // P2
