"""spfft_tpu.obs: run-metrics registry and plan cards.

Three contract layers (ISSUE 1 acceptance):

* registry — no-op-when-disabled (shared singletons, zero per-call
  allocation on the hot path), snapshot schema stability (JSON round-trip +
  validate_snapshot), Prometheus rendering;
* plan cards — schema-complete across local/distributed, XLA/MXU, all three
  SPMD exchange disciplines and the 2-D pencil decomposition, with the
  rejected-alternative costs matching ``parallel/policy.py``'s accounting
  exactly (card and resolver read the same table, so a mismatch here means
  the card lies about what the policy weighed);
* surfaces — ``programs/report.py`` emits a document that passes
  ``obs.validate_report``.
"""
import importlib.util
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    obs,
)
from spfft_tpu.obs.plancard import base_discipline
from spfft_tpu.parallel.policy import alternative_costs, round_cost_bytes
from spfft_tpu.parameters import distribute_triplets
from spfft_tpu.types import wire_scalar_bytes
from utils import random_sparse_triplets, split_values


@pytest.fixture(autouse=True)
def fresh_registry():
    """Each test sees an empty, enabled registry and leaves it that way."""
    obs.clear()
    obs.enable()
    yield
    obs.clear()
    obs.enable()


# ---- registry ----------------------------------------------------------------


def test_disabled_instruments_are_shared_noops():
    obs.disable()
    try:
        assert not obs.is_enabled()
        # zero-allocation contract: every disabled instrument is THE shared
        # singleton, regardless of name/labels, and records nothing
        c1 = obs.counter("a_total")
        c2 = obs.counter("b_total", direction="backward")
        g = obs.gauge("c")
        h = obs.histogram("d_seconds")
        assert c1 is c2 is g is h
        assert obs.phase_timer("d_seconds") is obs.phase_timer("e_seconds")
        c1.inc(5)
        g.set(2.0)
        h.observe(0.1)
        with obs.phase_timer("d_seconds"):
            pass
        snap = obs.snapshot()
        assert snap["counters"] == {}
        assert snap["gauges"] == {}
        assert snap["histograms"] == {}
        assert snap["enabled"] is False
    finally:
        obs.enable()


def test_disabled_transform_path_records_nothing():
    obs.disable()
    try:
        trip = random_sparse_triplets(np.random.default_rng(0), 8, 8, 8, 0.5)
        t = Transform(
            ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip
        )
        values = np.arange(len(trip)).astype(np.complex128)
        t.backward(values)
        t.forward(scaling=ScalingType.FULL)
        snap = obs.snapshot()
        assert snap["counters"] == {} and snap["histograms"] == {}
    finally:
        obs.enable()


def test_metrics_env_knob_disables_at_import():
    """SPFFT_TPU_METRICS=0 gates the registry at import, before any user
    code runs (the compile-time analogue of the reference's SPFFT_TIMING)."""
    r = subprocess.run(
        [
            sys.executable, "-c",
            "from spfft_tpu import obs\n"
            "assert not obs.is_enabled()\n"
            "assert obs.counter('a') is obs.counter('b', x='y')\n"
            "obs.counter('a').inc()\n"
            "assert obs.snapshot()['counters'] == {}\n"
            "print('ok')\n",
        ],
        env={**os.environ, "SPFFT_TPU_METRICS": "0", "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=240,
    )
    assert r.returncode == 0, r.stderr[-1000:]
    assert "ok" in r.stdout


def test_snapshot_schema_and_json_roundtrip():
    obs.counter("transforms_total", direction="backward", engine="xla").inc()
    obs.gauge("capacity").set(3.5)
    h = obs.histogram("wait_seconds", direction="backward")
    for v in (1e-6, 5e-4, 2.0, 100.0):
        h.observe(v)
    snap = obs.snapshot()
    # schema stability: exactly these top-level keys, tagged schema id
    assert sorted(snap) == ["counters", "enabled", "gauges", "histograms", "schema"]
    assert snap["schema"] == obs.SNAPSHOT_SCHEMA == "spfft_tpu.obs.snapshot/1"
    assert obs.validate_snapshot(snap) == []
    assert json.loads(json.dumps(snap)) == snap
    key = 'transforms_total{direction="backward",engine="xla"}'
    assert snap["counters"][key] == 1
    hist = snap["histograms"]['wait_seconds{direction="backward"}']
    assert hist["count"] == 4
    assert hist["min"] == 1e-6 and hist["max"] == 100.0
    # cumulative buckets end at the total count under +Inf
    assert hist["buckets"]["+Inf"] == 4
    assert obs.validate_snapshot({"schema": "bogus/9"})  # flags unknown schema


def test_prometheus_text_renders_all_kinds():
    obs.counter("transforms_total", engine="xla").inc(3)
    obs.gauge("capacity").set(1.0)
    obs.histogram("wait_seconds").observe(0.5)
    text = obs.prometheus_text()
    assert "# TYPE spfft_tpu_transforms_total counter" in text
    assert 'spfft_tpu_transforms_total{engine="xla"} 3' in text
    assert "# TYPE spfft_tpu_wait_seconds histogram" in text
    assert 'spfft_tpu_wait_seconds_bucket{le="+Inf"} 1' in text
    assert "spfft_tpu_wait_seconds_count 1" in text
    # one TYPE line per metric name even with several label sets
    obs.counter("transforms_total", engine="mxu").inc()
    text = obs.prometheus_text()
    assert text.count("# TYPE spfft_tpu_transforms_total counter") == 1


def test_prometheus_text_golden():
    """Golden exposition output: counter/gauge/histogram rendered byte-exact
    — cumulative `le` buckets ending at the total count, one TYPE line per
    metric, label values escaped per the Prometheus text format (backslash,
    double-quote, newline)."""
    obs.counter("transforms_total", direction="backward", engine="xla").inc(2)
    obs.counter("transforms_total", direction="forward", engine="xla").inc()
    # label-value escaping: quotes, backslashes and newlines must not break
    # the exposition line
    obs.counter("odd_labels_total", path='a"b', note="c\\d\ne").inc()
    obs.gauge("capacity", unit="slots").set(3.5)
    h = obs.histogram("wait_seconds", direction="backward")
    for v in (5e-6, 5e-6, 2e-4, 0.5, 100.0):
        h.observe(v)
    golden = "\n".join(
        [
            "# TYPE spfft_tpu_odd_labels_total counter",
            'spfft_tpu_odd_labels_total{note="c\\\\d\\ne",path="a\\"b"} 1',
            "# TYPE spfft_tpu_transforms_total counter",
            'spfft_tpu_transforms_total{direction="backward",engine="xla"} 2',
            'spfft_tpu_transforms_total{direction="forward",engine="xla"} 1',
            "# TYPE spfft_tpu_capacity gauge",
            'spfft_tpu_capacity{unit="slots"} 3.5',
            "# TYPE spfft_tpu_wait_seconds histogram",
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="1e-05"} 2',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="0.0001"} 2',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="0.001"} 3',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="0.01"} 3',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="0.1"} 3',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="1.0"} 4',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="10.0"} 4',
            'spfft_tpu_wait_seconds_bucket{direction="backward",le="+Inf"} 5',
            'spfft_tpu_wait_seconds_sum{direction="backward"} 100.50021',
            'spfft_tpu_wait_seconds_count{direction="backward"} 5',
            "",
        ]
    )
    assert obs.prometheus_text() == golden


def test_phase_timer_records_duration():
    with obs.phase_timer("dispatch_seconds", direction="forward"):
        pass
    snap = obs.snapshot()
    hist = snap["histograms"]['dispatch_seconds{direction="forward"}']
    assert hist["count"] == 1 and hist["sum"] >= 0.0


# ---- run counters through the public API ------------------------------------


def test_local_transform_records_counters():
    trip = random_sparse_triplets(np.random.default_rng(1), 8, 8, 8, 0.5)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip)
    values = np.arange(len(trip)).astype(np.complex128)
    t.backward(values)
    t.forward(scaling=ScalingType.FULL)
    snap = obs.snapshot()
    assert (
        snap["counters"]['transforms_total{direction="backward",engine="xla"}'] == 1
    )
    assert (
        snap["counters"]['transforms_total{direction="forward",engine="xla"}'] == 1
    )
    staged = [k for k in snap["counters"] if k.startswith("staged_bytes_total")]
    assert staged and all(snap["counters"][k] > 0 for k in staged)
    assert (
        snap["histograms"]['wait_seconds{direction="backward"}']["count"] == 1
    )
    assert (
        snap["histograms"]['dispatch_seconds{direction="forward"}']["count"] == 1
    )


def test_distributed_transform_records_wire_bytes():
    rng = np.random.default_rng(2)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.5)
    per_shard = distribute_triplets(trip, 4, 8)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, per_shard,
        mesh=sp.make_fft_mesh(4),
    )
    vps = split_values(per_shard, trip, values)
    t.backward(vps)
    t.forward(scaling=ScalingType.FULL)
    snap = obs.snapshot()
    key = 'exchange_wire_bytes_total{engine="xla"}'
    # one repartition per direction, both accounted at the plan's wire volume
    assert snap["counters"][key] == 2 * t.exchange_wire_bytes()


# ---- plan cards --------------------------------------------------------------


def _local_plan(engine, dim=8):
    trip = random_sparse_triplets(np.random.default_rng(3), dim, dim, dim, 0.5)
    return Transform(
        ProcessingUnit.HOST, TransformType.C2C, dim, dim, dim,
        indices=trip, engine=engine,
    )


@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_local_plan_card(engine):
    card = _local_plan(engine).report()
    assert obs.validate_plan_card(card) == []
    assert card["kind"] == "local"
    assert card["engine"] == engine
    assert card["dims"] == [8, 8, 8]
    assert 0 < card["nnz_fraction"] <= 1
    assert json.loads(json.dumps(card)) == card
    if engine == "mxu":
        # the MXU engine's measured decisions ride in the card
        assert card["execution"]["sparse_y"]["variant"] in (
            "per-slot", "blocked", "dense"
        )
        assert "crossover_sy_over_y" in card["execution"]["sparse_y"]


def test_local_plan_card_compiled_stats():
    card = _local_plan("xla").report(include_compiled=True)
    assert obs.validate_plan_card(card) == []
    compiled = card["compiled"]
    assert compiled["compile_seconds"] > 0
    assert isinstance(compiled["hlo_op_classes"], dict) and compiled["hlo_op_classes"]
    assert isinstance(compiled["element_granular_ops"], int)
    assert json.loads(json.dumps(card)) == card


def _distributed_plan(exchange, engine="mxu", shards=4):
    rng = np.random.default_rng(4)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.6)
    per_shard = distribute_triplets(trip, shards, 8)
    return DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, per_shard,
        mesh=sp.make_fft_mesh(shards), exchange_type=exchange, engine=engine,
    )


_DISCIPLINES = [
    ExchangeType.BUFFERED,
    ExchangeType.COMPACT_BUFFERED,
    ExchangeType.UNBUFFERED,
]


@pytest.mark.parametrize("exchange", _DISCIPLINES + [ExchangeType.DEFAULT])
@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_distributed_plan_card_matches_policy_accounting(exchange, engine):
    """The card's exchange_policy table IS policy.py's accounting — chosen
    and rejected alternatives carry the same bytes/rounds/cost the DEFAULT
    resolver weighs for this geometry (ISSUE 1 acceptance)."""
    t = _distributed_plan(exchange, engine)
    card = t.report()
    assert obs.validate_plan_card(card) == []
    assert card["kind"] == "distributed"
    assert card["decomposition"] == "slab"
    assert card["num_shards"] == 4
    assert json.loads(json.dumps(card)) == card

    # the active exchange section reflects the plan's real accounting
    assert card["exchange"]["wire_bytes"] == t.exchange_wire_bytes()
    assert card["exchange"]["rounds"] == t.exchange_rounds()
    assert card["exchange"]["transport"] in (
        "all_to_all", "ragged_all_to_all", "one-shot chain", "ppermute chain"
    )

    policy = card["exchange_policy"]
    assert policy["round_cost_bytes"] == round_cost_bytes()
    p = t._params
    table = alternative_costs(
        p.num_sticks_per_shard,
        p.local_z_lengths,
        one_shot_supported=policy["one_shot_supported"],
        wire_scalar_bytes=wire_scalar_bytes(t.exchange_type, t.dtype),
    )
    assert len(policy["alternatives"]) == len(table) == 3
    chosen_rows = 0
    for alt in policy["alternatives"]:
        row = table[ExchangeType[alt["discipline"]]]
        assert alt["wire_bytes"] == row["wire_bytes"]
        assert alt["rounds"] == row["rounds"]
        assert alt["cost_bytes"] == row["cost_bytes"]
        chosen_rows += alt["chosen"]
    assert chosen_rows == 1
    (chosen_alt,) = [a for a in policy["alternatives"] if a["chosen"]]
    assert chosen_alt["discipline"] == base_discipline(t.exchange_type).name
    rejected = [a for a in policy["alternatives"] if not a["chosen"]]
    assert len(rejected) == 2  # >= 1 rejected alternative with full accounting
    if exchange == ExchangeType.DEFAULT:
        # the resolver picked the cheapest row of this very table
        assert chosen_alt["cost_bytes"] == min(
            a["cost_bytes"] for a in policy["alternatives"]
        )


@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_pencil_plan_card_carries_policy_table(engine):
    """DEFAULT pencil plans stash the cost table the in-engine resolver
    weighed (pencil2._resolve_pencil2_default), chosen marked, alternatives
    priced per the same wire-bytes + round-cost model."""
    rng = np.random.default_rng(5)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.6)
    t = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, trip,
        mesh=sp.make_fft_mesh2(2, 2), engine=engine,
    )
    card = t.report()
    assert obs.validate_plan_card(card) == []
    assert card["decomposition"] == "pencil2"
    assert card["mesh"] == {"fft": 2, "fft2": 2}
    assert json.loads(json.dumps(card)) == card
    policy = card["exchange_policy"]
    assert policy["round_cost_bytes"] == round_cost_bytes()
    assert policy["chosen"] == t.exchange_type.name
    assert len(policy["alternatives"]) == 3
    (chosen_alt,) = [a for a in policy["alternatives"] if a["chosen"]]
    # the resolver minimizes cost_bytes over exactly this table
    assert chosen_alt["cost_bytes"] == min(
        a["cost_bytes"] for a in policy["alternatives"]
    )
    assert [a for a in policy["alternatives"] if not a["chosen"]]
    # an explicit discipline skips the resolver: no policy table, still valid
    t2 = DistributedTransform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, trip,
        mesh=sp.make_fft_mesh2(2, 2), engine=engine,
        exchange_type=ExchangeType.BUFFERED,
    )
    card2 = t2.report()
    assert obs.validate_plan_card(card2) == []
    assert "exchange_policy" not in card2


def test_grid_report():
    g = sp.Grid(8, 8, 8, 64, ProcessingUnit.HOST)
    card = g.report()
    assert card["kind"] == "grid"
    assert card["max_dims"] == [8, 8, 8]
    assert card["num_shards"] == 1
    assert json.loads(json.dumps(card)) == card


# ---- report CLI surface ------------------------------------------------------


def test_report_cli_emits_valid_document(tmp_path):
    spec = importlib.util.spec_from_file_location(
        "report", Path(__file__).resolve().parent.parent / "programs" / "report.py"
    )
    report_mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(report_mod)
    out = tmp_path / "report.json"
    rc = report_mod.main(
        ["-d", "8", "8", "8", "--no-compiled", "-o", str(out)]
    )
    assert rc == 0
    doc = json.loads(out.read_text())
    assert obs.validate_report(doc) == []
    assert doc["plan"]["dims"] == [8, 8, 8]
    assert any(
        k.startswith("transforms_total") for k in doc["metrics"]["counters"]
    )
