"""CopyPlan regression coverage for pipe shapes that miscompiled on TPU.

A TPU (v5e) XLA fusion bug produced wrong values when a pipe concatenated >= 2
lane-shifted pieces whose sublane counts were below the 8-row f32 tile (Rk=2,
two distinct shifts); lanecopy.apply now materializes the pieces behind an
optimization_barrier before the concat. These tests pin the shape classes —
they pass on CPU either way, and exercise the fixed path directly on TPU.
"""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from spfft_tpu.ops.lanecopy import LANE, CopyPlan


def _check(src_of_dst, num_src, seed=0):
    plan = CopyPlan.build(np.asarray(src_of_dst, dtype=np.int64), num_src)
    assert plan is not None
    rng = np.random.default_rng(seed)
    flat = rng.standard_normal(num_src).astype(np.float32)
    got = np.asarray(plan.apply(jnp.asarray(flat))).reshape(-1)[: len(src_of_dst)]
    want = np.where(
        np.asarray(src_of_dst) >= 0,
        flat[np.maximum(np.asarray(src_of_dst), 0)],
        0.0,
    )
    np.testing.assert_array_equal(got, want)
    # apply_pair must be exactly two independent applies in BOTH settings of
    # SPFFT_TPU_PAIR_COPY — every pipe shape class checked here also pins the
    # stacked (2, rows, LANE) path, which is off by default but must not rot
    # (it shares _apply_stacked with apply, including the sub-tile concat
    # miscompile workaround).
    flat_b = rng.standard_normal(num_src).astype(np.float32)
    saved = os.environ.get("SPFFT_TPU_PAIR_COPY")
    for pair_env in ("0", "1"):
        os.environ["SPFFT_TPU_PAIR_COPY"] = pair_env
        try:
            pa, pb = plan.apply_pair(jnp.asarray(flat), jnp.asarray(flat_b))
        finally:
            if saved is None:
                os.environ.pop("SPFFT_TPU_PAIR_COPY", None)
            else:
                os.environ["SPFFT_TPU_PAIR_COPY"] = saved
        np.testing.assert_array_equal(
            np.asarray(pa), np.asarray(plan.apply(jnp.asarray(flat)))
        )
        np.testing.assert_array_equal(
            np.asarray(pb), np.asarray(plan.apply(jnp.asarray(flat_b)))
        )
    return plan


def test_two_block_two_shift_pipe():
    # Two destination blocks whose second runs start at different unaligned
    # source offsets -> an Rk=2 pipe with two distinct shifts (the TPU
    # miscompile shape).
    m = np.full(2 * LANE, -1, dtype=np.int64)
    m[:40] = np.arange(5, 45)            # block 0 run: shift 5
    m[40:128] = np.arange(300, 388)      # block 0 second run: shift (300-40)%128
    m[128:200] = np.arange(77, 149)      # block 1 run: shift 77
    m[200:256] = np.arange(500, 556)     # block 1 second run
    plan = _check(m, 600)
    assert any(p.rows_sorted.size == 2 for p in plan.pipes)


def test_many_small_pipes_random_sticks():
    # Random stick-like layout: variable-length contiguous runs at arbitrary
    # offsets, producing a mix of pipe widths including sub-tile ones.
    rng = np.random.default_rng(42)
    pieces, src = [], 0
    for _ in range(37):
        ln = int(rng.integers(3, 97))
        gap = int(rng.integers(0, 30))
        pieces.append(np.full(gap, -1, dtype=np.int64))
        pieces.append(np.arange(src, src + ln))
        src += ln + int(rng.integers(0, 11))
    m = np.concatenate(pieces)
    _check(m, src + 1, seed=1)


def test_disjoint_same_base_segments_use_full_mask():
    """Two dst segments in one block with the SAME affine base (src - lane)
    form one run with a non-contiguous mask — the f32-mask fallback path
    (the range-mask fast path only handles contiguous valid-lane runs)."""
    m = np.full(LANE, -1, dtype=np.int64)
    m[0:10] = np.arange(100, 110)    # base 100
    m[20:30] = np.arange(120, 130)   # base 100 again (120 - 20)
    plan = _check(m, 200, seed=5)
    assert any(p.mask is not None for p in plan.pipes)


def test_contiguous_masks_use_range_form():
    """Ordinary stick layouts compile to range-form masks (no f32 constant)."""
    m = np.full(4 * LANE, -1, dtype=np.int64)
    m[5:120] = np.arange(115)
    m[130:300] = np.arange(200, 370)
    plan = _check(m, 400, seed=6)
    assert all(p.mask is None for p in plan.pipes)


def test_empty_block_hole_padding():
    """Layouts with fully-empty 128-lane blocks exercise the pipe-0 padding
    that promotes near-full pipes to the direct-write path (a spherical plan
    has a handful of empty blocks out of tens of thousands)."""
    # 20 blocks with one fully-empty block (19/20 = 95% covered, above the 90%
    # padding threshold), the others dense-ish at assorted unaligned offsets.
    m = np.full(20 * LANE, -1, dtype=np.int64)
    src = 0
    for b in range(20):
        if b == 11:
            continue  # fully-empty block
        ln = 100 + (b % 3) * 9
        m[b * LANE : b * LANE + ln] = np.arange(src + 5, src + 5 + ln)
        src += ln + 13
    plan = _check(m, src + 40, seed=3)
    # the padding must have promoted pipe 0 to full coverage (direct write)
    assert plan.pipes[0].block_ids is None


def test_empty_block_padding_not_applied_when_sparse():
    """Below the dense-coverage threshold (SPFFT_TPU_COPY_DENSE_FRAC, 0.1)
    the scatter-add path is kept — padding a genuinely sparse pipe to full
    coverage would gather mostly dummy rows."""
    m = np.full(20 * LANE, -1, dtype=np.int64)
    m[0:LANE] = np.arange(7, 7 + LANE)  # only 1 of 20 blocks covered (5%)
    plan = _check(m, 400, seed=4)
    assert plan.pipes[0].block_ids is not None


def test_partial_coverage_pipes_promoted_to_dense():
    """Pipes covering >= the dense threshold are padded to full coverage:
    the row-scatter-add lowering measured ~70 ns/row on TPU at 512^3
    (BASELINE.md round 4) — direct write + dense add wins far below full
    coverage."""
    # 7 of 10 blocks covered (70%, the 512^3 decompress shape class)
    m = np.full(10 * LANE, -1, dtype=np.int64)
    for b in range(7):
        m[b * LANE : (b + 1) * LANE] = np.arange(b * LANE, (b + 1) * LANE)
    plan = _check(m, 10 * LANE, seed=5)
    assert plan.pipes[0].block_ids is None


@pytest.mark.parametrize("shift_pair", [(1, 127), (5, 77), (0, 64)])
def test_single_pipe_two_shifts(shift_pair):
    s0, s1 = shift_pair
    m = np.full(2 * LANE, -1, dtype=np.int64)
    m[:LANE] = np.arange(s0, s0 + LANE)
    m[LANE:] = np.arange(400 + s1, 400 + s1 + LANE)
    _check(m, 700, seed=2)
