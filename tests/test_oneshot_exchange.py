"""One-shot UNBUFFERED exchange (parallel/ragged.py OneShotExchange).

The reference's UNBUFFERED transpose is a single MPI_Alltoallw with derived
datatypes — exact counts, one call (reference:
src/transpose/transpose_mpi_unbuffered_host.cpp:51-176). Here that discipline
is a single ragged-all-to-all collective on backends that compile the HLO, and
the same one-shot buffer layout over a ppermute chain elsewhere (XLA:CPU —
what these tests run). The transform-level tests exercise the chain
transport; the *_via_emulation tests additionally validate the ragged
transport's exact collective call contract (offsets/sizes/output placement)
against a ppermute-built emulation of ragged_all_to_all, so the only thing
left to the TPU bench is the HLO implementation itself.
"""
import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parallel.ragged import OneShotExchange, RaggedExchange
from spfft_tpu.parameters import distribute_triplets
from utils import random_sparse_triplets, split_values

ENGINES = ["xla", "mxu"]
PU = {"xla": ProcessingUnit.HOST, "mxu": ProcessingUnit.GPU}


def build(engine, num_shards, dims, per_shard, exchange, dtype=None, **kw):
    dx, dy, dz = dims
    return DistributedTransform(
        PU[engine],
        TransformType.C2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(num_shards),
        exchange_type=exchange,
        engine=engine,
        dtype=dtype,
        **kw,
    )


def test_unbuffered_is_a_distinct_implementation():
    """Three enum disciplines -> three implementations: padded all_to_all
    (no ragged object), COMPACT chain (RaggedExchange), UNBUFFERED one-shot
    (OneShotExchange)."""
    rng = np.random.default_rng(0)
    dims = (8, 8, 8)
    trip = random_sparse_triplets(rng, *dims, 0.5)
    per_shard = distribute_triplets(trip, 4, dims[1])
    t_pad = build("xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.BUFFERED)
    t_cmp = build(
        "xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.COMPACT_BUFFERED
    )
    t_one = build(
        "xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.UNBUFFERED
    )
    assert t_pad._exec._ragged is None
    assert type(t_cmp._exec._ragged) is RaggedExchange
    assert type(t_one._exec._ragged) is OneShotExchange


@pytest.mark.parametrize("engine", ENGINES)
@pytest.mark.parametrize("seed", range(4))
def test_oneshot_matches_padded_fuzz(engine, seed):
    """Randomized ragged geometries: UNBUFFERED must produce the same transform
    as the padded discipline (identical FFT stages; only the repartition
    differs)."""
    rng = np.random.default_rng(100 + seed)
    num_shards = int(rng.choice([2, 3, 5, 8]))
    dims = tuple(int(d) for d in rng.integers(4, 14, size=3))
    dx, dy, dz = dims
    triplets = random_sparse_triplets(
        rng, dx, dy, dz, float(rng.uniform(0.2, 0.8)),
        z_fill=float(rng.uniform(0.4, 1.0)),
    )
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(
        len(triplets)
    )
    weights = rng.uniform(0.1, 1.0, size=num_shards)
    per_shard = distribute_triplets(triplets, num_shards, dy, weights=weights)
    vps = split_values(per_shard, triplets, values)

    outs = {}
    for exchange in (ExchangeType.BUFFERED, ExchangeType.UNBUFFERED):
        t = build(engine, num_shards, dims, [p.copy() for p in per_shard], exchange)
        outs[exchange] = (
            t.backward([v.copy() for v in vps]),
            t.forward(scaling=ScalingType.FULL),
        )
    b_pad, f_pad = outs[ExchangeType.BUFFERED]
    b_one, f_one = outs[ExchangeType.UNBUFFERED]
    scale = max(1.0, float(np.abs(b_pad).max()))
    np.testing.assert_allclose(b_one, b_pad, rtol=0, atol=1e-11 * scale)
    for r in range(num_shards):
        np.testing.assert_allclose(f_one[r], f_pad[r], rtol=0, atol=1e-11)


def test_oneshot_wire_bytes_are_exact_alltoallv_volume():
    """UNBUFFERED's byte accounting is exact rows x the full L_max row width
    (the round-5 row-granular ragged-all-to-all unit is an L_max-wide row):
    sum_{i != j} n_i * L_max — never above the COMPACT chain's per-step
    window volume, and strictly below the padded volume on stick-imbalanced
    plans."""
    rng = np.random.default_rng(7)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    triplets = random_sparse_triplets(rng, dx, dy, dz, 0.4)
    skew = [triplets] + [np.zeros((0, 3), dtype=np.int64)] * 3
    lz = [1, 1, 1, dz - 3]
    kw = dict(local_z_lengths=lz)
    t_pad = build("xla", 4, dims, [p.copy() for p in skew], ExchangeType.BUFFERED, **kw)
    t_cmp = build(
        "xla", 4, dims, [p.copy() for p in skew], ExchangeType.COMPACT_BUFFERED, **kw
    )
    t_one = build(
        "xla", 4, dims, [p.copy() for p in skew], ExchangeType.UNBUFFERED, **kw
    )
    one, cmp_, pad = (
        t.exchange_wire_bytes() for t in (t_one, t_cmp, t_pad)
    )
    # stick-skewed: the one-shot's exact rows undercut the padded volume
    # 4x here; the row-granular chain windows tie the padded volume
    assert one < pad and cmp_ == pad
    # exact volume, computed independently from the plan geometry
    p = t_one._exec.params
    n = np.asarray(p.num_sticks_per_shard, dtype=np.int64)
    L = np.asarray(p.local_z_lengths, dtype=np.int64)
    rowvol = int(n.sum()) * (len(n) - 1) * int(max(1, L.max()))
    scalar = 2 * np.dtype(t_one._exec.real_dtype).itemsize
    assert one == rowvol * scalar


def test_exchange_rounds_accounting():
    """Latency accounting: padded and one-shot-ragged report 1 round, the
    COMPACT chain P-1 (the chain-transport fallback also reports P-1)."""
    rng = np.random.default_rng(8)
    dims = (8, 8, 8)
    trip = random_sparse_triplets(rng, *dims, 0.5)
    per_shard = distribute_triplets(trip, 4, dims[1])
    t_pad = build("xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.BUFFERED)
    t_cmp = build(
        "xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.COMPACT_BUFFERED
    )
    t_one = build(
        "xla", 4, dims, [p.copy() for p in per_shard], ExchangeType.UNBUFFERED
    )
    assert t_pad._exec.exchange_rounds() == 1
    assert t_cmp._exec.exchange_rounds() == 3
    one = t_one._exec
    expected = 1 if one._ragged.transport == "ragged" else 3
    assert one.exchange_rounds() == expected


@pytest.mark.parametrize("engine", ENGINES)
def test_oneshot_r2c(engine):
    """Distributed R2C through the one-shot exchange (hermitian completion
    downstream of the one-shot unpack)."""
    rng = np.random.default_rng(9)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    real = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(real) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    per_shard = distribute_triplets(trip, 4, dy)
    vps = [freq[t_[:, 2], t_[:, 1], t_[:, 0]] for t_ in per_shard]
    t = DistributedTransform(
        PU[engine],
        TransformType.R2C,
        dx,
        dy,
        dz,
        per_shard,
        mesh=sp.make_fft_mesh(4),
        exchange_type=ExchangeType.UNBUFFERED,
        engine=engine,
    )
    out = t.backward([v.copy() for v in vps])
    np.testing.assert_allclose(out, real, rtol=0, atol=1e-10)
    back = t.forward(scaling=ScalingType.FULL)
    for r in range(4):
        np.testing.assert_allclose(back[r], vps[r], rtol=0, atol=1e-10)


def test_oneshot_run_twice_zeroing():
    """The reference runs every transform twice to catch stale-memory bugs
    (reference: tests/test_util/test_transform.hpp:129-131); the one-shot
    buffers are rebuilt in-trace so the second run must match the first."""
    rng = np.random.default_rng(10)
    dims = (9, 7, 10)
    trip = random_sparse_triplets(rng, *dims, 0.6)
    per_shard = distribute_triplets(trip, 5, dims[1])
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    vps = split_values(per_shard, trip, values)
    t = build("mxu", 5, dims, per_shard, ExchangeType.UNBUFFERED)
    first = t.backward([v.copy() for v in vps])
    second = t.backward([v.copy() for v in vps])
    np.testing.assert_array_equal(np.asarray(first), np.asarray(second))


def test_oneshot_block_exchange_geometry():
    """OneShotBlockExchange (the pencil engines' UNBUFFERED form): the static
    ROW-offset tables must tile each shard's send/recv row buffers exactly —
    segment [off, off+rows) ranges are disjoint, ordered, and the sender's
    size table is the transpose of the receiver's (the ragged-all-to-all
    invariant send_sizes == all_to_all(recv_sizes)). Since round 5 the ragged
    unit is one C-wide ROW (whole-row gathers on pack/unpack; element-unit
    packing measured ~20 ns/element on TPU — bench_results/
    round5_pencil_bisect2.json), so offsets/sizes count rows and the wire
    ships rows x C. Numerics run on TPU (the HLO is unavailable on XLA:CPU;
    CPU plans fall back to the chain class, which the pencil2 tests cover)."""
    from spfft_tpu.parallel.ragged import (
        OneShotBlockExchange,
        RaggedBlockExchange,
    )

    rng = np.random.default_rng(11)
    P1, P2 = 2, 3
    P = P1 * P2
    R, C = 7, 5
    rows = rng.integers(0, R + 1, size=(P, P))
    cols = rng.integers(0, C + 1, size=(P, P))
    one = OneShotBlockExchange(("fft", "fft2"), (P1, P2), rows, cols, R, C)
    chain = RaggedBlockExchange(("fft", "fft2"), (P1, P2), rows, cols, R, C)
    for reverse in (False, True):
        r, off_in, off_recv, send_rows, recv_rows = one._geom[reverse]
        r_expect = (rows.T if reverse else rows).astype(np.int64)
        assert (r == r_expect).all()
        for s in range(P):
            # sender s: destination row segments tile [0, sum) in order
            ends = off_in[s] + r[s]
            assert off_in[s][0] == 0
            assert (off_in[s][1:] == ends[:-1]).all()
            assert ends[-1] <= send_rows
            # receiver s: source row segments tile [0, sum) in order
            ends_r = off_recv[:, s] + r[:, s]
            assert off_recv[0, s] == 0
            assert (off_recv[1:, s] == ends_r[:-1]).all()
            assert ends_r[-1] <= recv_rows
        # cross-implementation check: the chain class derives its per-step
        # 2-D buffer dims independently (per-distance maxima over the same
        # rows/cols geometry); its size table must be their products
        r64, c64 = (rows.T, cols.T) if reverse else (rows, cols)
        s_idx = np.arange(P)
        for k in range(P):
            step_r = max(1, int(r64[s_idx, (s_idx + k) % P].max()))
            step_c = max(1, int(c64[s_idx, (s_idx + k) % P].max()))
            assert (step_r, step_c) == chain._dims[reverse][k]
            assert step_r * step_c == chain._sizes[reverse][k]
    # row-granular volume accounting: exact off-diagonal rows x full C width
    off_rows = int(rows.sum() - np.diag(rows).sum())
    assert one.offwire_elems() == off_rows * C
    assert one.rounds() == 1 and chain.rounds() == P - 1


def _emulated_ragged_all_to_all(axis_names, axis_sizes):
    """Reference emulation of jax.lax.ragged_all_to_all built from ppermute:
    step k ships the ENTIRE (buffer, offsets, sizes) of the distance-k source
    and copies out the one segment addressed to this shard. O(P * N) wire —
    test-only — but semantically exact: it also checks the caller's axis_name
    and cross-checks recv_sizes against the sender-side send_sizes each step
    (a mismatch poisons the output with NaN so the comparison fails), so
    patching it in validates the one-shot exchanges' full collective call
    contract on backends without the HLO."""
    import jax
    import jax.numpy as jnp

    from spfft_tpu.parallel.ragged import _fold_axis_index

    P = int(np.prod(axis_sizes))

    def emu(operand, output, input_offsets, send_sizes, output_offsets,
            recv_sizes, *, axis_name=None, axis_index_groups=None):
        assert axis_index_groups is None
        names = (axis_name,) if isinstance(axis_name, str) else tuple(axis_name)
        assert names == tuple(axis_names), (names, axis_names)
        me = _fold_axis_index(axis_names, axis_sizes)
        out = output
        n_out = output.shape[0]
        idx = jnp.arange(n_out, dtype=jnp.int32)
        for k in range(P):
            perm = [(i, (i + k) % P) for i in range(P)]
            # after this ppermute I hold the buffers of src = me - k
            op_s = jax.lax.ppermute(operand, axis_names, perm)
            in_off_s = jax.lax.ppermute(input_offsets, axis_names, perm)
            sz_s = jax.lax.ppermute(send_sizes, axis_names, perm)
            out_off_s = jax.lax.ppermute(output_offsets, axis_names, perm)
            # the segment src sends to ME: src-side tables indexed by me
            src = (me - k) % P
            src_off = in_off_s[me]
            size = sz_s[me]
            dst_off = out_off_s[me]
            take = jnp.clip(idx - dst_off + src_off, 0, op_s.shape[0] - 1)
            seg = op_s[take]
            # contract check: my recv_sizes[src] must equal what src sends me
            seg = jnp.where(recv_sizes[src] == size, seg, jnp.nan)
            mask = (idx >= dst_off) & (idx < dst_off + size)
            mask = mask.reshape(mask.shape + (1,) * (out.ndim - 1))
            out = jnp.where(mask, seg, out)
        return out

    return emu


@pytest.mark.parametrize("seed", range(3))
def test_oneshot_ragged_transport_matches_chain_via_emulation(seed, monkeypatch):
    """Run the 1-D one-shot exchange with transport='ragged' against an
    emulated ragged_all_to_all and compare to the chain transport on the same
    geometry — validating the exact offsets/sizes the TPU HLO will receive."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P_

    from spfft_tpu.parallel.ragged import OneShotExchange

    rng = np.random.default_rng(300 + seed)
    P = int(rng.choice([3, 4, 6]))
    Z = int(rng.integers(6, 12))
    S = int(rng.integers(2, 5))
    n = rng.integers(0, S + 1, size=P)
    if n.sum() == 0:
        n[0] = 1
    # random contiguous z-slabs
    cuts = np.sort(rng.choice(np.arange(1, Z), size=P - 1, replace=False))
    bounds = np.concatenate([[0], cuts, [Z]])
    L = np.diff(bounds)
    zo = bounds[:-1]
    Lm = int(L.max())
    nslots = P * S + 3
    # unique plane slots for the real sticks
    yx = np.full(P * S, nslots, dtype=np.int64)
    slots = rng.permutation(nslots)[: int(n.sum())]
    si = 0
    for r in range(P):
        yx[r * S : r * S + n[r]] = slots[si : si + n[r]]
        si += n[r]

    args = (n, L, zo, S, Lm, Z, nslots, yx)
    one_ragged = OneShotExchange(*args, transport="ragged")
    one_chain = OneShotExchange(*args, transport="chain")

    devs = jax.devices()[:P]
    if len(devs) < P:
        pytest.skip(f"needs {P} devices")
    mesh = Mesh(np.asarray(devs), ("fft",))
    # raising=False: runtimes older than the ragged-all-to-all HLO binding
    # have no attribute to replace — the emulation IS the binding there
    monkeypatch.setattr(
        jax.lax, "ragged_all_to_all",
        _emulated_ragged_all_to_all(("fft",), (P,)), raising=False,
    )

    sticks = rng.standard_normal((P, S, Z)).astype(np.float32)
    sharding = NamedSharding(mesh, P_("fft", None, None))
    x = jax.device_put(sticks, sharding)

    def run(ex):
        def f(part):
            flats = ex.backward((part[0],))
            back = ex.forward((flats[0],))
            return flats[0][None], back[0][None]

        from spfft_tpu.parallel.mesh import shard_mapper

        g = jax.jit(
            shard_mapper(mesh)(
                f, in_specs=P_("fft", None, None),
                out_specs=(P_("fft", None), P_("fft", None, None)),
            )
        )
        return g(x)

    planes_r, sticks_r = run(one_ragged)
    planes_c, sticks_c = run(one_chain)
    np.testing.assert_allclose(np.asarray(planes_r), np.asarray(planes_c), atol=0)
    np.testing.assert_allclose(np.asarray(sticks_r), np.asarray(sticks_c), atol=0)
    # forward(backward) recovers the real sticks (padding rows may differ)
    for r in range(P):
        np.testing.assert_allclose(
            np.asarray(sticks_r)[r, : n[r]], sticks[r, : n[r]], atol=1e-6
        )


@pytest.mark.parametrize("seed", range(2))
def test_oneshot_block_ragged_transport_matches_chain_via_emulation(seed, monkeypatch):
    """OneShotBlockExchange (the pencil engines' UNBUFFERED form) against the
    emulated ragged_all_to_all, compared to RaggedBlockExchange on identical
    geometry — both directions (reverse=False/True)."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P_

    from spfft_tpu.parallel.ragged import (
        OneShotBlockExchange,
        RaggedBlockExchange,
    )

    rng = np.random.default_rng(500 + seed)
    P1, P2 = (2, 2) if seed == 0 else (3, 2)
    P = P1 * P2
    R, C = 4, 5
    rows = rng.integers(0, R + 1, size=(P, P))
    cols = rng.integers(0, C + 1, size=(P, P))
    one = OneShotBlockExchange(("fft", "fft2"), (P1, P2), rows, cols, R, C)
    chain = RaggedBlockExchange(("fft", "fft2"), (P1, P2), rows, cols, R, C)

    devs = jax.devices()[:P]
    if len(devs) < P:
        pytest.skip(f"needs {P} devices")
    mesh = Mesh(np.asarray(devs).reshape(P1, P2), ("fft", "fft2"))
    monkeypatch.setattr(
        jax.lax,
        "ragged_all_to_all",
        _emulated_ragged_all_to_all(("fft", "fft2"), (P1, P2)),
        raising=False,
    )

    # blocks with exact valid rectangles (sender-direction tables), zero padding
    data = np.zeros((P, P, R, C), dtype=np.float32)
    for s in range(P):
        for d in range(P):
            data[s, d, : rows[s, d], : cols[s, d]] = rng.standard_normal(
                (rows[s, d], cols[s, d])
            )
    sharding = NamedSharding(mesh, P_(("fft", "fft2"), None, None, None))
    x = jax.device_put(data, sharding)

    for reverse in (False, True):
        if reverse:
            xr = jax.device_put(np.swapaxes(data, 0, 1).copy(), sharding)
        else:
            xr = x

        def run(ex, xin):
            def f(part):
                out = ex.exchange([part[0]], reverse=reverse)
                return out[0][None]

            from spfft_tpu.parallel.mesh import shard_mapper

            g = jax.jit(
                shard_mapper(mesh)(
                    f,
                    in_specs=P_(("fft", "fft2"), None, None, None),
                    out_specs=P_(("fft", "fft2"), None, None, None),
                )
            )
            return np.asarray(g(xin))

        got_one = run(one, xr)
        got_chain = run(chain, xr)
        np.testing.assert_allclose(got_one, got_chain, atol=0, err_msg=f"reverse={reverse}")
