"""MXU engine correctness vs the dense oracle (runs on CPU; same math as TPU)."""
import numpy as np
import pytest

from spfft_tpu.execution_mxu import MxuLocalExecution
from spfft_tpu.ops.lanecopy import CopyPlan, build_compress_plan, build_decompress_plan
from spfft_tpu.parameters import make_local_parameters
from spfft_tpu.types import ScalingType, TransformType
from utils import assert_close, oracle_backward_c2c, oracle_forward_c2c, random_sparse_triplets

DIMS = [(4, 5, 6), (11, 12, 13), (16, 16, 16), (1, 13, 7), (100, 11, 2)]


def sorted_triplets(trip, dims):
    """Stick-major, z-ascending caller order (the lanecopy fast path)."""
    dx, dy, dz = dims
    t = np.asarray(trip)
    xs = np.where(t[:, 0] < 0, t[:, 0] + dx, t[:, 0])
    ys = np.where(t[:, 1] < 0, t[:, 1] + dy, t[:, 1])
    zs = np.where(t[:, 2] < 0, t[:, 2] + dz, t[:, 2])
    return t[np.lexsort((zs, xs * dy + ys))]


@pytest.mark.parametrize("dims", DIMS)
@pytest.mark.parametrize("order", ["sorted", "random"])
def test_mxu_c2c_backward_forward(dims, order):
    rng = np.random.default_rng(31)
    dx, dy, dz = dims
    # whole sticks for the sorted fast path (<=2 affine runs per block); ragged
    # z-fill + shuffle for the general fallback path
    if order == "sorted":
        trip = sorted_triplets(random_sparse_triplets(rng, dx, dy, dz, 0.5, 1.0), dims)
    else:
        trip = random_sparse_triplets(rng, dx, dy, dz, 0.5, 0.8)
        rng.shuffle(trip)
    params = make_local_parameters(TransformType.C2C, dx, dy, dz, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float64)
    if order == "sorted":
        assert ex._decompress_plan is not None, "sorted order must hit the fast path"

    n = params.num_values
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    out = ex.backward(values)  # host API returns (Z, Y, X)
    expected = oracle_backward_c2c(trip, values, dx, dy, dz)
    assert_close(out, expected)
    assert_close(ex.backward(values), expected)  # run twice

    space = rng.standard_normal((dz, dy, dx)) + 1j * rng.standard_normal((dz, dy, dx))
    got = ex.forward(space)
    assert_close(got[0] + 1j * got[1], oracle_forward_c2c(trip, space))
    got = ex.forward(space, ScalingType.FULL)
    assert_close(
        got[0] + 1j * got[1], oracle_forward_c2c(trip, space, scale=1.0 / (dx * dy * dz))
    )


@pytest.mark.parametrize("dims", DIMS)
def test_mxu_r2c_roundtrip(dims):
    rng = np.random.default_rng(32)
    dx, dy, dz = dims
    xs = np.arange(dx // 2 + 1)
    trip = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    params = make_local_parameters(TransformType.R2C, dx, dy, dz, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float64)

    r = rng.standard_normal((dz, dy, dx))
    vre, vim = ex.forward(r, ScalingType.FULL)
    out = ex.backward(np.asarray(vre) + 1j * np.asarray(vim))
    assert out.dtype == np.float64
    assert_close(out, r)


def test_mxu_r2c_redundant_omitted():
    rng = np.random.default_rng(33)
    dx, dy, dz = 6, 6, 6
    r = rng.standard_normal((dz, dy, dx))
    freq = np.fft.fftn(r) / (dx * dy * dz)
    trip = []
    for x in range(dx // 2 + 1):
        for y in range(dy):
            if x == 0 and y > dy // 2:
                continue
            for z in range(dz):
                if x == 0 and y == 0 and z > dz // 2:
                    continue
                trip.append((x, y, z))
    trip = np.asarray(trip)
    params = make_local_parameters(TransformType.R2C, dx, dy, dz, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float64)
    values = freq[trip[:, 2], trip[:, 1], trip[:, 0]]
    assert_close(ex.backward(values), r)


def test_mxu_f32_precision():
    """HIGHEST-precision matmul DFT must hold ~1e-5 relative in f32."""
    rng = np.random.default_rng(34)
    dims = (32, 32, 32)
    dx, dy, dz = dims
    trip = sorted_triplets(random_sparse_triplets(rng, dx, dy, dz, 0.5), dims)
    params = make_local_parameters(TransformType.C2C, dx, dy, dz, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float32)
    n = params.num_values
    values = (rng.standard_normal(n) + 1j * rng.standard_normal(n)).astype(np.complex64)
    out = ex.backward(values)
    expected = oracle_backward_c2c(trip, values, dx, dy, dz)
    scale = np.abs(expected).max()
    np.testing.assert_allclose(out, expected, rtol=0, atol=3e-5 * scale)


# ---- lanecopy unit tests -------------------------------------------------------


def test_copyplan_identity_and_holes():
    rng = np.random.default_rng(35)
    n = 1000
    # dst = src shifted by 7 with holes every 13th slot
    src_of_dst = np.arange(n) - 7
    src_of_dst[src_of_dst < 0] = -1
    src_of_dst[::13] = -1
    plan = CopyPlan.build(src_of_dst, n)
    assert plan is not None
    vals = rng.standard_normal(n)
    import jax.numpy as jnp

    out = np.asarray(plan.apply(jnp.asarray(vals))).reshape(-1)[: n]
    want = np.where(src_of_dst >= 0, vals[np.maximum(src_of_dst, 0)], 0.0)
    np.testing.assert_allclose(out, want, atol=0)


def test_copyplan_fragmented_returns_none():
    rng = np.random.default_rng(36)
    n = 512
    src_of_dst = rng.permutation(n)  # fully random: ~128 runs per block
    assert CopyPlan.build(src_of_dst, n) is None


def test_copyplan_round_trip_through_plans():
    """decompress plan then compress plan reproduces the packed values."""
    rng = np.random.default_rng(37)
    dims = (8, 8, 8)
    dx, dy, dz = dims
    trip = sorted_triplets(random_sparse_triplets(rng, dx, dy, dz, 0.6, 1.0), dims)
    params = make_local_parameters(TransformType.C2C, dx, dy, dz, trip)
    n, S = params.num_values, params.num_sticks
    dplan = build_decompress_plan(params.value_indices, S * dz, n)
    cplan = build_compress_plan(params.value_indices, S * dz)
    assert dplan is not None and cplan is not None
    import jax.numpy as jnp

    vals = rng.standard_normal(n)
    slots = np.asarray(dplan.apply(jnp.asarray(vals))).reshape(-1)[: S * dz]
    back = np.asarray(cplan.apply(jnp.asarray(slots))).reshape(-1)[:n]
    np.testing.assert_allclose(back, vals, atol=0)


def test_transform_engine_mxu_parity():
    """Transform(engine='mxu') matches engine='xla' through the public API."""
    from spfft_tpu import ProcessingUnit, Transform

    rng = np.random.default_rng(38)
    dims = (8, 9, 10)
    dx, dy, dz = dims
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5, centered=True)
    n = len(trip)
    values = rng.standard_normal(n) + 1j * rng.standard_normal(n)

    tm = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip, engine="mxu")
    tx = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip, engine="xla")
    assert_close(tm.backward(values), tx.backward(values))
    assert_close(tm.forward(scaling=ScalingType.FULL), tx.forward(scaling=ScalingType.FULL))
    assert_close(tm.space_domain_data(), tx.space_domain_data())
    c = tm.clone()
    assert c._engine == "mxu"
    assert_close(c.backward(values), tx.backward(values))


@pytest.mark.parametrize("ttype", [TransformType.C2C, TransformType.R2C])
def test_lane_alignment_rotation_path(ttype):
    """The lane-alignment stick rotations (plan_alignment_rotations + the
    phase undo around the z matmuls + CopyPlan.apply's shift-0 fast path) only
    engage when dim_z is a LANE multiple and the caller order is
    stick-contiguous — production sizes, which the small-dim tests never
    reach. Pin the whole path at dz=128 against the dense oracle."""
    from spfft_tpu import ProcessingUnit, Transform

    rng = np.random.default_rng(77)
    dx, dy, dz = 6, 7, 128
    r2c = ttype == TransformType.R2C
    # meshgrid-style stick-contiguous order with a contiguous wrapped-z run
    # per stick (the plane-wave layout the rotation targets)
    trips = []
    ys = range(-((dy - 1) // 2), dy // 2 + 1)
    # R2C: non-negative x, excluding the even-dx Nyquist plane (its internal
    # conjugate redundancy is the caller's responsibility, as in the reference)
    xs = range((dx + 1) // 2) if r2c else range(-((dx - 1) // 2), dx // 2 + 1)
    for x in xs:
        for y in ys:
            if rng.random() < 0.3:
                continue
            h = int(rng.integers(3, dz // 2))
            if r2c and x == 0 and y < 0:
                continue  # redundant half of the x == 0 plane
            lo = 0 if (r2c and x == 0 and y == 0) else -h
            trips.extend((x, y, z) for z in range(lo, h + 1))
    trip = np.asarray(trips)

    if r2c:
        real = rng.standard_normal((dz, dy, dx))
        freq = np.fft.fftn(real) / (dx * dy * dz)
        values = freq[trip[:, 2], trip[:, 1], trip[:, 0]]
    else:
        values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))

    t = Transform(ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip, engine="mxu")
    assert t._exec._phase is not None, "rotation path must engage at dz=128"
    for plan in (t._exec._decompress_plan, t._exec._compress_plan):
        assert all(
            p.shift_counts[0] == p.rows_sorted.size for p in plan.pipes
        ), "every pipe must be shift-0 aligned"

    out = t.backward(values)
    if r2c:
        # the sparse stick set does not span the full spectrum, so compare
        # against the unrotated XLA engine (hermitian completion included)
        tx = Transform(ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip, engine="xla")
        assert_close(out, tx.backward(values))
    else:
        assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back, values)


def test_map_chunked_pads_non_divisible_batch():
    """map_chunked must handle any chunk count via zero-padding (a prime batch
    must not fall back to per-row serialization), and the engine's chunked
    x-stages must stay exact when forced on, including a non-divisible batch."""
    import jax.numpy as jnp

    from spfft_tpu.ops import fft as offt

    x = np.arange(21.0).reshape(7, 3)  # 7 rows, 4 chunks -> pad to 8
    out = offt.map_chunked(lambda a: a * 2.0, (jnp.asarray(x),), 4)
    np.testing.assert_allclose(np.asarray(out), x * 2.0)
    pair = offt.map_chunked(
        lambda a, b: (a + b, a - b), (jnp.asarray(x), jnp.asarray(x * 3)), 2
    )
    np.testing.assert_allclose(np.asarray(pair[0]), x * 4.0)
    np.testing.assert_allclose(np.asarray(pair[1]), x * -2.0)

    from spfft_tpu import ProcessingUnit, Transform

    rng = np.random.default_rng(9)
    dx, dy, dz = 8, 10, 8
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                  indices=trip, engine="mxu")
    t._exec._x_stage_chunks = 3  # force chunking (pad 10 -> 12) before first trace
    out = t.backward(values)
    assert_close(out, oracle_backward_c2c(trip, values, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back, values)


def test_sparse_y_stage_opt_in(monkeypatch):
    """SPFFT_TPU_SPARSE_Y=1 forces the per-slot y-DFT contraction (no
    expand/pack stages; auto mode gates on the measured Sy/Y crossover —
    see test_sparse_y_auto_threshold). Must agree with the dense path and
    compose with the alignment rotations."""
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y", "1")
    from spfft_tpu import ProcessingUnit, Transform
    import spfft_tpu as sp

    rng = np.random.default_rng(83)
    # spherical workload at dz=128: rotations AND sparse-y both engage
    # (dy=32 so the widest y-chord, ~0.6*dy, stays below the full extent
    # after 8-padding)
    dx, dy, dz = 16, 32, 128
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, 0.6)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                  indices=trip, engine="mxu")
    assert t._exec._sparse_y, "sparse-y must engage on a spherical plan"
    assert t._exec._phase is not None, "rotations must compose with sparse-y"
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    out = t.backward(v)
    assert_close(out, oracle_backward_c2c(trip, v, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back, v)

    # near-dense y occupancy: the compaction cannot win -> stays disengaged
    dense_trip = sorted_triplets(
        random_sparse_triplets(rng, 8, 8, 8, 0.9, 1.0), (8, 8, 8)
    )
    t2 = Transform(ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8,
                   indices=dense_trip, engine="mxu")
    assert not t2._exec._sparse_y


def test_phase_rep_in_trace_matches_table(monkeypatch):
    """Forcing the compact ("delta") phase representation must reproduce the
    table path exactly: the in-trace cos/sin generation reduces delta*k mod Z
    in int32 before the float cast, so both forms agree to f32 rounding. The
    compact form is what keeps 512^3-class plans compilable (the (S, Z)
    tables are hundreds of MB of HLO constants otherwise — BASELINE.md)."""
    from spfft_tpu import ProcessingUnit, Transform
    from spfft_tpu.ops import lanecopy

    rng = np.random.default_rng(5)
    dx, dy, dz = 5, 6, 128
    trips = []
    for x in range(dx):
        for y in range(dy):
            if rng.random() < 0.3:
                continue
            h = int(rng.integers(3, dz // 2))
            trips.extend((x, y, z) for z in range(dz - h, dz))  # wrapped runs
            trips.extend((x, y, z) for z in range(h))
    trip = np.asarray(trips)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))

    t_table = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                        indices=trip, engine="mxu")
    assert t_table._exec._phase is not None and t_table._exec._phase[0] == "table"

    monkeypatch.setenv(lanecopy.PHASE_TABLE_LIMIT_MB_ENV, "0")
    t_delta = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                        indices=trip, engine="mxu")
    assert t_delta._exec._phase is not None and t_delta._exec._phase[0] == "delta"

    out_t = t_table.backward(values)
    out_d = t_delta.backward(values)
    np.testing.assert_allclose(out_d, out_t, rtol=1e-5, atol=1e-5)
    back_t = t_table.forward(scaling=ScalingType.FULL)
    back_d = t_delta.forward(scaling=ScalingType.FULL)
    np.testing.assert_allclose(back_d, back_t, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(back_d, values, rtol=1e-4, atol=1e-4)


def test_sparse_y_blocked_stage(monkeypatch):
    """Blocked sparse-y (the win region ABOVE the per-slot crossover,
    ops/fft.plan_sparse_y_blocked): exact stick table, per-bucket padded y
    contractions, bucket-major slot permutation folded into the x matrices.
    Must agree with the dense oracle in both directions and compose with the
    alignment rotations."""
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, Transform

    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y", raising=False)
    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y_BLOCKS", raising=False)
    rng = np.random.default_rng(19)
    dx, dy, dz = 32, 32, 128  # dz=128 so the alignment rotations engage too
    # headline-class spherical density: per-slot sparse-y stays off
    # (Sy/Y ~ 0.69 > 0.6), the blocked variant engages (row total < 0.8 A*Y)
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, 0.659)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                  indices=trip, engine="mxu")
    assert not t._exec._sparse_y
    assert t._exec._sparse_y_blocked is not None, "blocked must auto-engage"
    assert t._exec._phase is not None, "rotations must compose"
    # padded bucket rows genuinely undercut the dense extent
    rows = sum(ri.size for ri, _, _ in t._exec._sparse_y_blocked)
    assert rows < 0.8 * t._exec._num_x_active * dy
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    out = t.backward(v)
    assert_close(out, oracle_backward_c2c(trip, v, dx, dy, dz))
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back, v)

    # forced bucket count; off switch; R2C never engages
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "2")
    t2 = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                   indices=trip, engine="mxu")
    assert len(t2._exec._sparse_y_blocked) == 2
    assert_close(t2.backward(v), oracle_backward_c2c(trip, v, dx, dy, dz))
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "0")
    t0 = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                   indices=trip, engine="mxu")
    assert t0._exec._sparse_y_blocked is None
    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y_BLOCKS", raising=False)


def test_sparse_y_blocked_r2c(monkeypatch):
    """R2C blocked sparse-y (round 5, VERDICT r4 item 3): the x == 0 plane
    rides as a trailing DENSE bucket so its hermitian fill sees the full y
    extent; every other slot keeps the exact per-bucket tables. Checked two
    ways: against the hermitian-extension oracle, and against the dense-path
    engine (blocks=0) on identical inputs — the two paths must agree to
    machine precision for ARBITRARY values (same fill semantics)."""
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, Transform

    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y", raising=False)
    rng = np.random.default_rng(41)
    dx, dy, dz = 16, 32, 32
    r = rng.standard_normal((dz, dy, dx))
    full = np.fft.fftn(r)
    trip = random_sparse_triplets(rng, dx, dy, dz, 0.5, hermitian=True)
    # drop unpaired x-Nyquist sticks (their mirror must come from the caller)
    hx = dx // 2
    stick_set = {(int(t[0]), int(t[1]) % dy) for t in trip}
    trip = trip[[
        i for i, t in enumerate(trip)
        if t[0] != hx or (hx, (-int(t[1])) % dy) in stick_set
    ]]
    assert (trip[:, 0] == 0).any(), "seed must produce x == 0 sticks"
    xs, ys, zs = trip[:, 0], trip[:, 1] % dy, trip[:, 2] % dz
    values = full[zs, ys, xs]

    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "2")
    tr = Transform(ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz,
                   indices=trip, engine="mxu")
    blk = tr._exec._sparse_y_blocked
    assert blk is not None, "R2C blocked must engage when forced"
    assert tr._exec._sy_x0_bucket == len(blk) - 1
    assert blk[tr._exec._sy_x0_bucket][0].shape == (1, dy)

    # hermitian-extension oracle
    dense = np.zeros((dz, dy, dx), dtype=np.complex128)
    dense[zs, ys, xs] = values
    dense[(-zs) % dz, (-ys) % dy, (-xs) % dx] = np.conj(values)
    expected = np.fft.ifftn(dense) * (dx * dy * dz)
    assert np.abs(expected.imag).max() < 1e-9
    out = np.asarray(tr.backward(values))
    assert_close(out, expected.real)
    back = tr.forward(scaling=ScalingType.FULL)
    assert_close(back, values)

    # dense-path equivalence on arbitrary (non-hermitian) values
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "0")
    t_dense = Transform(ProcessingUnit.HOST, TransformType.R2C, dx, dy, dz,
                        indices=trip, engine="mxu")
    assert t_dense._exec._sparse_y_blocked is None
    w = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    np.testing.assert_allclose(
        np.asarray(tr.backward(w)), np.asarray(t_dense.backward(w)),
        rtol=1e-9, atol=1e-9,
    )


def test_sparse_y_blocks_knob_validation(monkeypatch):
    """SPFFT_TPU_SPARSE_Y_BLOCKS is validated like SPFFT_TPU_SPARSE_Y:
    'auto'/'0'/positive int, descriptive typed InvalidParameterError
    otherwise (advisor r4; typed-error discipline SA010)."""
    from spfft_tpu.errors import InvalidParameterError
    from spfft_tpu.ops import fft as offt

    xslot = np.asarray([0, 0, 1])
    ys = np.asarray([0, 1, 0])
    for bad in ("banana", "-3", "1.5"):
        monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", bad)
        with pytest.raises(InvalidParameterError, match="SPFFT_TPU_SPARSE_Y_BLOCKS"):
            offt.plan_sparse_y_blocked(xslot, ys, 8, np.float32, 3, 16)


def test_sparse_y_blocked_operand_path(monkeypatch):
    """SPFFT_TPU_SPARSE_Y_MATRIX_MB=0 forces the bucket matrices onto the
    jit-operand path (the 512^3 compile-transport fix); results must match
    the embedded-constant path exactly (same constants, different plumbing)."""
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, Transform

    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_BLOCKS", "3")
    rng = np.random.default_rng(31)
    dx = dy = dz = 32
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, 0.659)
    v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))

    t_embed = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                        indices=trip, engine="mxu", dtype=np.float32)
    assert len(t_embed._exec.phase_operands) == 0

    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y_MATRIX_MB", "0")
    t_ops = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                      indices=trip, engine="mxu", dtype=np.float32)
    assert len(t_ops._exec.phase_operands) == 12  # 3 buckets x 4 matrices
    # host numpy matrices are freed once operands thread
    assert all(wyb is None for _, wyb, _ in t_ops._exec._sparse_y_blocked)

    # same constants, different plumbing — but XLA may fold embedded
    # constants differently than parameters, so allow ulp-level slack
    out_e = t_embed.backward(v)
    out_o = t_ops.backward(v)
    np.testing.assert_allclose(
        np.asarray(out_e), np.asarray(out_o), rtol=1e-6, atol=1e-5
    )
    back_e = t_embed.forward(scaling=ScalingType.FULL)
    back_o = t_ops.forward(scaling=ScalingType.FULL)
    np.testing.assert_allclose(
        np.asarray(back_e), np.asarray(back_o), rtol=1e-6, atol=1e-5
    )


def test_sparse_y_auto_threshold(monkeypatch):
    """Unset (auto) sparse-y engages only below the measured Sy/Y < 0.6
    crossover; =0 forces it off even there; =1 forces it on above it."""
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, Transform

    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y", raising=False)
    dx, dy, dz = 16, 32, 128
    # sharp cutoff: widest y-chord well under 0.6 * dy -> auto engages
    # (radius 0.4 -> Sy = 16 = 0.5 * dy after 8-padding at these dims)
    sharp = sp.create_spherical_cutoff_triplets(dx, dy, dz, 0.4)
    t = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                  indices=sharp, engine="mxu")
    assert t._exec._sparse_y, "auto mode must engage at a sharp cutoff"

    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y", "0")
    t0 = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                   indices=sharp, engine="mxu")
    assert not t0._exec._sparse_y

    # above-threshold cutoff (radius 0.5 -> Sy = 24 = 0.75 * dy at these
    # dims): auto stays off, =1 forces the stage on — both paths must agree
    monkeypatch.delenv("SPFFT_TPU_SPARSE_Y", raising=False)
    wide = sp.create_spherical_cutoff_triplets(dx, dy, dz, 0.5)
    tw = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                   indices=wide, engine="mxu")
    assert not tw._exec._sparse_y, "auto mode must stay off above the crossover"
    monkeypatch.setenv("SPFFT_TPU_SPARSE_Y", "1")
    tf = Transform(ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
                   indices=wide, engine="mxu")
    assert tf._exec._sparse_y, "=1 must force the stage on above the crossover"
    v = np.random.default_rng(7).standard_normal(len(wide))
    out = tf.backward(v + 1j * v)
    outw = tw.backward(v + 1j * v)
    np.testing.assert_allclose(out, outw, rtol=1e-4, atol=1e-4)
