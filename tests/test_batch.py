"""Batch-fused execution (SPFFT_TPU_BATCH_FUSE, spfft_tpu.ir batch axis).

Five contracts:

1. **Batched == looped parity fuzz** over {C2C, R2C} x {f32, f64} x
   {local xla, local mxu, slab, pencil} on random (ragged-membership)
   sparse sets, seeded through the ``SPFFT_TPU_FUZZ_SEED`` machinery.
2. **One dispatch per batch per direction** —
   ``ir_dispatches_total{mode="batched"}`` counts exactly 1 for a whole
   batch, locally and on the 4-device meshes.
3. **Degradation** — fault site ``ir.batch`` armed: the batch degrades to
   the split-phase per-request loop with ``batch_fuse_failed`` on the plan
   card and parity intact — never a failed batch; the knob is
   typed-validated and ``0`` disables cleanly.
4. **Tuner-owned batch size** — ``fused/bN`` candidates measured on the
   plan's own batched programs, winner persisted in wisdom, warm store
   reproduces with zero trials.
5. **Serving integration** — the coalescing batcher routes same-geometry
   batches (per-caller value orders bridged by order maps) through ONE
   stacked program with NO plan clones leased (the lazy-leasing bugfix);
   the legacy loop still leases; chaos on ``ir.batch`` keeps every ticket
   resolving correctly; sched-mode runs a batch as one task.
"""
import numpy as np
import pytest

from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    faults,
    obs,
    tuning,
)
from spfft_tpu.errors import InvalidParameterError
from spfft_tpu.parallel.mesh import make_fft_mesh, make_fft_mesh2
from spfft_tpu.parameters import distribute_triplets
from test_ir import _case_values, _tol, case_id, fuzz_rng
from utils import random_sparse_triplets


@pytest.fixture(autouse=True)
def _clean_state(monkeypatch):
    monkeypatch.delenv("SPFFT_TPU_BATCH_FUSE", raising=False)
    monkeypatch.delenv("SPFFT_TPU_FUSE", raising=False)
    yield


def _batched_counts():
    out = {}
    for key, value in obs.snapshot()["counters"].items():
        if not key.startswith("ir_dispatches_total"):
            continue
        for direction in ("backward", "forward"):
            if f'mode="batched"' in key and f'direction="{direction}"' in key:
                out[direction] = value
    return out


def _delta(before, after):
    return {
        d: after.get(d, 0) - before.get(d, 0) for d in ("backward", "forward")
    }


def _batch_values(rng, trip, dims, r2c, dtype, batch):
    return [_case_values(rng, trip, dims, r2c, dtype) for _ in range(batch)]


# ---------------------------------------------------------------------------
# parity fuzz: batched vs looped, local engines
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("r2c", [False, True])
@pytest.mark.parametrize("engine", ["xla", "mxu"])
def test_parity_batched_vs_looped_local(dtype, r2c, engine):
    rng = fuzz_rng(11000, case_id(np.dtype(dtype).name, r2c, engine))
    dims = (
        int(rng.integers(6, 11)),
        int(rng.integers(6, 11)),
        int(rng.integers(6, 12)),
    )
    trip = random_sparse_triplets(
        rng, *dims, float(rng.uniform(0.4, 0.9)), hermitian=r2c
    )
    tt = TransformType.R2C if r2c else TransformType.C2C
    B = int(rng.integers(2, 5))
    vals = _batch_values(rng, trip, dims, r2c, dtype, B)

    t = Transform(
        ProcessingUnit.HOST, tt, *dims, indices=trip, dtype=dtype,
        engine=engine, fuse=True,
    )
    ref = Transform(
        ProcessingUnit.HOST, tt, *dims, indices=trip, dtype=dtype,
        engine=engine, fuse=True,
    )
    before = _batched_counts()
    outs = t.backward_batch(vals)
    fwd = t.forward_batch(outs, ScalingType.FULL)
    after = _batched_counts()
    # the single-dispatch proof: ONE batched program call per direction for
    # the whole batch
    assert _delta(before, after) == {"backward": 1, "forward": 1}
    tol = _tol(dtype)
    for b in range(B):
        np.testing.assert_allclose(
            outs[b], ref.backward(vals[b]), rtol=tol, atol=tol
        )
        np.testing.assert_allclose(
            fwd[b], ref.forward(scaling=ScalingType.FULL), rtol=tol, atol=tol
        )
    card = t.report()
    assert card["batch"]["enabled"] and not card["batch"]["failed"]
    assert B in card["batch"]["sizes"]
    assert obs.validate_plan_card(card) == []


# ---------------------------------------------------------------------------
# parity fuzz: batched vs looped, mesh engines (ragged membership)
# ---------------------------------------------------------------------------


def _mesh_case(rng, r2c, pencil):
    dims = (
        int(rng.integers(6, 10)),
        int(rng.integers(6, 10)),
        int(rng.integers(8, 13)),
    )
    trip = random_sparse_triplets(
        rng, *dims, float(rng.uniform(0.4, 0.9)), hermitian=r2c
    )
    if pencil:
        mesh = make_fft_mesh2(2, 2)
        ax = dict(zip(mesh.axis_names, mesh.devices.shape))
        psh = distribute_triplets(
            trip, 4, dims[1], layout=(int(ax["fft"]), int(ax["fft2"])),
            dim_x=dims[0],
        )
    else:
        mesh = make_fft_mesh(4)
        psh = distribute_triplets(trip, 4, dims[1])
    return dims, trip, mesh, psh


def _per_shard_values(psh, trip, values):
    lut = {tuple(x): v for x, v in zip(map(tuple, trip), values)}
    return [np.asarray([lut[tuple(x)] for x in s]) for s in psh]


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
@pytest.mark.parametrize("r2c", [False, True])
@pytest.mark.parametrize("pencil", [False, True], ids=["slab", "pencil"])
def test_parity_batched_vs_looped_mesh(dtype, r2c, pencil):
    rng = fuzz_rng(12000, case_id(np.dtype(dtype).name, r2c, pencil))
    dims, trip, mesh, psh = _mesh_case(rng, r2c, pencil)
    tt = TransformType.R2C if r2c else TransformType.C2C
    B = 2
    batches = [
        _per_shard_values(
            psh, trip, _case_values(rng, trip, dims, r2c, dtype)
        )
        for _ in range(B)
    ]
    t = DistributedTransform(
        ProcessingUnit.HOST, tt, *dims, psh, mesh=mesh, dtype=dtype,
        fuse=True,
    )
    ref = DistributedTransform(
        ProcessingUnit.HOST, tt, *dims, psh, mesh=mesh, dtype=dtype,
        fuse=True,
    )
    before = _batched_counts()
    outs = t.backward_batch(batches)
    fwd = t.forward_batch(outs, ScalingType.FULL)
    after = _batched_counts()
    assert _delta(before, after) == {"backward": 1, "forward": 1}
    tol = _tol(dtype)
    for b in range(B):
        np.testing.assert_allclose(
            outs[b], ref.backward(batches[b]), rtol=tol, atol=10 * tol
        )
        expect = ref.forward(outs[b], ScalingType.FULL)
        for got, want in zip(fwd[b], expect):
            np.testing.assert_allclose(got, want, rtol=tol, atol=10 * tol)
    assert not t.report()["batch"]["failed"]


# ---------------------------------------------------------------------------
# degradation: the ir.batch rung, knob surface
# ---------------------------------------------------------------------------


def _local_case(seed=0):
    rng = fuzz_rng(13000, seed)
    trip = random_sparse_triplets(rng, 8, 8, 8, 0.7)
    vals = [
        rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
        for _ in range(3)
    ]
    return trip, vals


def test_ir_batch_fault_degrades_to_loop_with_parity():
    trip, vals = _local_case(0)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=True,
    )
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    before = _batched_counts()
    with faults.inject("ir.batch=raise"):
        outs = t.backward_batch(vals)
        fwd = t.forward_batch(outs, ScalingType.FULL)
    after = _batched_counts()
    # never a failed batch: the split-phase loop answered, zero batched
    # dispatches, the rung on the card
    assert _delta(before, after) == {"backward": 0, "forward": 0}
    for b, v in enumerate(vals):
        np.testing.assert_allclose(
            outs[b], ref.backward(v), rtol=1e-9, atol=1e-9
        )
        np.testing.assert_allclose(fwd[b], v, rtol=1e-6, atol=1e-6)
    card = t.report()
    assert card["batch"]["failed"] and not card["batch"]["enabled"]
    assert any(
        d["event"] == "batch_fuse_failed" for d in card["degradations"]
    )
    assert obs.validate_plan_card(card) == []


def test_batch_fuse_env_validation(monkeypatch):
    trip, vals = _local_case(1)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    monkeypatch.setenv("SPFFT_TPU_BATCH_FUSE", "2")
    with pytest.raises(InvalidParameterError):
        t.backward_batch(vals)


def test_batch_fuse_off_loops_cleanly(monkeypatch):
    monkeypatch.setenv("SPFFT_TPU_BATCH_FUSE", "0")
    trip, vals = _local_case(2)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    before = _batched_counts()
    outs = t.backward_batch(vals)
    after = _batched_counts()
    assert _delta(before, after) == {"backward": 0, "forward": 0}
    for b, v in enumerate(vals):
        np.testing.assert_allclose(
            outs[b], ref.backward(v), rtol=1e-9, atol=1e-9
        )
    card = t.report()
    # a disabled knob is a configuration, not a failure
    assert not card["batch"]["enabled"] and not card["batch"]["failed"]
    assert card["batch"]["requested"] == "env"


def test_staged_path_has_no_batch_axis():
    trip, vals = _local_case(3)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=False,
    )
    assert not t._exec._ir.batch_available()
    outs = t.backward_batch(vals)  # loops, no rung
    assert len(outs) == len(vals)
    assert not t.report()["batch"]["failed"]


def test_batch_section_schema_pinned():
    from spfft_tpu.ir.compile import BATCH_KEYS
    from spfft_tpu.obs.plancard import BATCH_SECTION_KEYS

    assert tuple(BATCH_KEYS) == tuple(BATCH_SECTION_KEYS)
    trip, _ = _local_case(4)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    card = t.report()
    assert obs.validate_plan_card(card) == []
    del card["batch"]["sizes"]
    assert any("batch.sizes" in m for m in obs.validate_plan_card(card))


# ---------------------------------------------------------------------------
# tuner-owned batch axis
# ---------------------------------------------------------------------------


def test_tuned_batch_axis_persists_in_wisdom(tmp_path, monkeypatch):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "0")
    tuning.clear_memory()
    trip, _ = _local_case(5)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        fuse=True,
    )
    choice, record = tuning.tuned_batch(t, batch_max=8)
    assert record["provenance"] == "wisdom" and record["hit"] is False
    measured = [row for row in record["trials"] if "ms" in row]
    assert {row["batch"] for row in measured} <= {1, 4, 8} and measured
    assert choice["batch"] in (1, 4, 8)
    # warm store: zero trials, same choice
    before = obs.snapshot()["counters"]
    choice2, record2 = tuning.tuned_batch(t, batch_max=8)
    after = obs.snapshot()["counters"]
    assert record2["hit"] is True and choice2 == choice
    trials_run = sum(
        after.get(k, 0) - before.get(k, 0)
        for k in after
        if k.startswith("tuning_trials_total")
    )
    assert trials_run == 0
    # a different coalescing bound is a different decision problem
    choice3, record3 = tuning.tuned_batch(t, batch_max=2)
    assert record3["hit"] is False
    assert all(row["batch"] <= 2 for row in record3["trials"] if "ms" in row)


def test_tuned_batch_model_fallback_without_cpu_trials(tmp_path, monkeypatch):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "wisdom.json"))
    monkeypatch.delenv(tuning.TUNE_CPU_ENV, raising=False)
    tuning.clear_memory()
    trip, _ = _local_case(6)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    choice, record = tuning.tuned_batch(t, batch_max=8)
    assert record["provenance"] == "model" and choice["batch"] is None


def test_batch_candidates_capped_by_batch_max():
    cands = tuning.batch_candidates(4)
    assert [c["batch"] for c in cands] == [1, 4]
    assert all(c["label"] == f"fused/b{c['batch']}" for c in cands)
    assert [c["batch"] for c in tuning.batch_candidates(None)] == [1, 4, 8]


# ---------------------------------------------------------------------------
# serving integration
# ---------------------------------------------------------------------------


def _serve_case(seed=0, dims=(12, 12, 12)):
    rng = fuzz_rng(14000, seed)
    trip = random_sparse_triplets(rng, *dims, 0.8)
    vals = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(
        len(trip)
    )
    return rng, trip, vals


def _submit_permuted(svc, rng, trip, vals, dims, n, **kw):
    tickets = []
    for i in range(n):
        perm = rng.permutation(len(trip))
        tickets.append(
            svc.submit(
                TransformType.C2C, dims, trip[perm], vals[perm],
                tenant=f"t{i % 2}", **kw,
            )
        )
    return tickets


def test_serve_batch_fused_no_clones_and_order_maps():
    from spfft_tpu.serve import TransformService

    dims = (12, 12, 12)
    rng, trip, vals = _serve_case(0, dims)
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, *dims, indices=trip,
    )
    expect = ref.backward(vals)
    with TransformService(start=False, batch_max=8) as svc:
        before = _batched_counts()
        tickets = _submit_permuted(svc, rng, trip, vals, dims, 6)
        svc.pump()
        after = _batched_counts()
        for tk in tickets:
            np.testing.assert_allclose(
                tk.result(timeout=60), expect, rtol=2e-4, atol=2e-4
            )
        entry = next(iter(svc.plans._entries.values()))
        # the lazy-leasing bugfix: a batch-fused entry never builds the
        # clone pool it would never use
        assert entry.clones == []
        assert after.get("backward", 0) - before.get("backward", 0) >= 1
        assert svc.describe()["config"]["batch_fuse"] is True


def test_serve_legacy_loop_still_leases(monkeypatch):
    from spfft_tpu.serve import TransformService

    monkeypatch.setenv("SPFFT_TPU_BATCH_FUSE", "0")
    dims = (12, 12, 12)
    rng, trip, vals = _serve_case(1, dims)
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, *dims, indices=trip,
    )
    expect = ref.backward(vals)
    with TransformService(start=False, batch_max=4) as svc:
        tickets = _submit_permuted(svc, rng, trip, vals, dims, 4)
        svc.pump()
        for tk in tickets:
            np.testing.assert_allclose(
                tk.result(timeout=60), expect, rtol=2e-4, atol=2e-4
            )
        entry = next(iter(svc.plans._entries.values()))
        assert len(entry.clones) == 3  # batch of 4 leased the pool
        assert svc.describe()["config"]["batch_fuse"] is False


def test_serve_chaos_ir_batch_every_ticket_resolves():
    from spfft_tpu.serve import TransformService

    dims = (12, 12, 12)
    rng, trip, vals = _serve_case(2, dims)
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, *dims, indices=trip,
    )
    expect = ref.backward(vals)
    with faults.inject("ir.batch=raise"):
        with TransformService(start=False, batch_max=8) as svc:
            tickets = _submit_permuted(svc, rng, trip, vals, dims, 5)
            svc.pump()
            for tk in tickets:
                np.testing.assert_allclose(
                    tk.result(timeout=60), expect, rtol=2e-4, atol=2e-4
                )
            assert svc.stats()["counts"].get("failed", 0) == 0


def test_serve_sched_mode_batch_as_one_task():
    from spfft_tpu.serve import TransformService

    dims = (12, 12, 12)
    rng, trip, vals = _serve_case(3, dims)
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, *dims, indices=trip,
    )
    expect = ref.backward(vals)
    with TransformService(start=False, batch_max=8, sched=True) as svc:
        before = _batched_counts()
        tickets = _submit_permuted(svc, rng, trip, vals, dims, 4)
        svc.pump()
        after = _batched_counts()
        for tk in tickets:
            np.testing.assert_allclose(
                tk.result(timeout=60), expect, rtol=2e-4, atol=2e-4
            )
        # one batch task -> one batched dispatch for the whole cycle
        assert after.get("backward", 0) - before.get("backward", 0) == 1
        entry = next(iter(svc.plans._entries.values()))
        assert entry.clones == []


def test_sched_batch_task_demotes_per_request():
    """A batch task whose primary dispatch fails demotes through the
    per-request reference rung — correct results, one demoted outcome."""
    from spfft_tpu import sched

    trip, vals = _local_case(7)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    ref = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    graph = sched.TaskGraph()
    tid = graph.add("backward", payload=list(vals), transform=t, batch=True)
    with faults.inject("sched.run=raise"):
        report = sched.run_graph(graph, retries=0, demote=True)
    assert report.outcomes[tid] == "demoted"
    results = report.results[tid]
    for b, v in enumerate(vals):
        np.testing.assert_allclose(
            results[b], ref.backward(v), rtol=1e-9, atol=1e-9
        )


def test_batch_task_validation_typed():
    from spfft_tpu import sched

    trip, vals = _local_case(8)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    graph = sched.TaskGraph()
    with pytest.raises(InvalidParameterError):
        graph.add("backward", payload=[], transform=t, batch=True)
    with pytest.raises(InvalidParameterError):
        graph.add("backward", payload=vals[0], transform=t, batch=True)
    with pytest.raises(InvalidParameterError):
        graph.add(
            "backward", payload=list(vals),
            spec={"transform_type": "C2C"}, batch=True,
        )


def test_guard_mode_scans_batched_outputs():
    """Guard-armed plans keep output poison detection on the batched path:
    a corrupted batched dispatch surfaces typed, never as silent data."""
    from spfft_tpu.errors import GenericError

    trip, vals = _local_case(9)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
        guard=True,
    )
    outs = t.backward_batch(vals)  # clean batch passes all checks
    assert len(outs) == len(vals)
    with faults.inject("engine.execute=corrupt"):
        with pytest.raises(GenericError):
            t.backward_batch(vals)


def test_batch_count_marks_padding_tail():
    """count= (the serving bucket-padding contract): only the real prefix
    is counted, guard-checked and returned."""
    trip, vals = _local_case(10)
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, 8, 8, 8, indices=trip,
    )
    padded = vals + [vals[-1]]  # bucket 4 from 3 real requests
    before = obs.snapshot()["counters"]
    outs = t.backward_batch(padded, count=3)
    after = obs.snapshot()["counters"]
    assert len(outs) == 3
    grown = {
        k: after.get(k, 0) - before.get(k, 0)
        for k in after
        if k.startswith("transforms_total") and 'direction="backward"' in k
    }
    assert sum(grown.values()) == 3, grown
    with pytest.raises(InvalidParameterError):
        t.backward_batch(padded, count=9)
