"""Smoke test for the profiler-capture recipe (programs/profile.py)."""
import importlib.util
import json
from pathlib import Path


def test_profile_cli_captures_trace(tmp_path, capsys):
    from spfft_tpu import timing
    from spfft_tpu.obs import perf

    spec = importlib.util.spec_from_file_location(
        "profile_cli", Path(__file__).resolve().parent.parent / "programs" / "profile.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "trace"
    try:
        mod.main(["-d", "16", "16", "16", "-r", "2", "-o", str(out), "--engine", "mxu"])
    finally:
        # main() enables the module-global timer; don't leak into other tests
        timing.disable()
        timing.clear()
    printed = capsys.readouterr().out
    # host timing tree always prints; the reference stage scopes must appear
    assert "traced roundtrips" in printed
    assert "backward" in printed and "forward" in printed
    # the per-stage breakdown is the perf layer's attributed report (one
    # timing discipline — no ad-hoc stage timers), emitted as a JSON line
    # that validates against the spfft_tpu.obs.perf/1 schema
    report = next(
        json.loads(line)
        for line in printed.splitlines()
        if line.startswith("{") and '"spfft_tpu.obs.perf/1"' in line
    )
    assert perf.validate_perf_report(report) == []
    assert report["device_count"] == 1
    total = sum(row["seconds"] for row in report["stages"])
    assert abs(total - report["seconds_per_pair"]) < 1e-9
    # CPU backend supports device capture: a profile run directory appears
    assert (out / "plugins" / "profile").exists()
