"""Smoke test for the profiler-capture recipe (programs/profile.py)."""
import importlib.util
from pathlib import Path


def test_profile_cli_captures_trace(tmp_path, capsys):
    from spfft_tpu import timing

    spec = importlib.util.spec_from_file_location(
        "profile_cli", Path(__file__).resolve().parent.parent / "programs" / "profile.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = tmp_path / "trace"
    try:
        mod.main(["-d", "16", "16", "16", "-r", "2", "-o", str(out), "--engine", "mxu"])
    finally:
        # main() enables the module-global timer; don't leak into other tests
        timing.disable()
        timing.clear()
    printed = capsys.readouterr().out
    # host timing tree always prints; the reference stage scopes must appear
    assert "traced roundtrips" in printed
    assert "backward" in printed and "forward" in printed
    # CPU backend supports device capture: a profile run directory appears
    assert (out / "plugins" / "profile").exists()
