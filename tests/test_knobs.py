"""The typed env-knob registry (``spfft_tpu.knobs``), swept whole.

Parametrized over EVERY registered knob — a new registration is covered
the moment it lands, with no test edit:

* the registered default round-trips through the knob's typed getter with
  the env unset (type coercion, floor clamping, None passthrough),
* the default round-trips through the ENV path too (set the env to the
  default's string form, get the same resolved value back),
* every malformed value raises typed ``InvalidParameterError`` — never a
  bare ``ValueError`` — naming the knob (int/float/bool kinds, and str
  kinds with a choices vocabulary; a free-form str knob has no malformed
  values),
* the regenerated docs knob table matches the registry exactly (the
  ``programs/gen_api_docs.py`` rendering vs the committed block between
  the ``knob-table`` markers in ``docs/details.md``) — both ways, row for
  row.
"""
from __future__ import annotations

import sys
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "programs"))

from spfft_tpu import knobs  # noqa: E402
from spfft_tpu.errors import InvalidParameterError  # noqa: E402

ALL_KNOBS = knobs.names()

MALFORMED = {
    "int": "not-an-int",
    "float": "not-a-float",
    "bool": "maybe",
}


def _expected_default(knob):
    d = knob.default
    if d is None:
        return None
    if knob.kind == "int":
        v = int(d)
        return max(int(knob.floor), v) if knob.floor is not None else v
    if knob.kind == "float":
        v = float(d)
        return max(float(knob.floor), v) if knob.floor is not None else v
    if knob.kind == "bool":
        return bool(d)
    return str(d)


@pytest.mark.parametrize("name", ALL_KNOBS)
def test_default_round_trips_through_typed_getter(name, monkeypatch):
    monkeypatch.delenv(name, raising=False)
    knob = knobs.REGISTRY[name]
    got = knobs.get(name)
    expected = _expected_default(knob)
    if knob.kind == "bool" and knob.default is None:
        expected = False  # bool(None): an unset bool knob resolves False
    assert got == expected, (name, got, expected)
    if got is not None and knob.choices:
        assert got in knob.choices, (name, got, knob.choices)


@pytest.mark.parametrize("name", ALL_KNOBS)
def test_default_round_trips_through_env(name, monkeypatch):
    knob = knobs.REGISTRY[name]
    if knob.default is None:
        # unset and empty-string are both "use the default" (shell idiom)
        monkeypatch.setenv(name, "")
        assert knobs.get(name) == _expected_default(knob) or (
            knob.kind == "bool" and knobs.get(name) is False
        )
        return
    if knob.kind == "bool":
        env_value = "1" if knob.default else "0"
    else:
        env_value = str(knob.default)
    monkeypatch.setenv(name, env_value)
    assert knobs.get(name) == _expected_default(knob), name


@pytest.mark.parametrize("name", ALL_KNOBS)
def test_malformed_value_raises_typed(name, monkeypatch):
    knob = knobs.REGISTRY[name]
    if knob.kind == "str":
        if not knob.choices:
            pytest.skip("free-form str knob: every value is well-formed")
        bad = "::definitely-not-a-choice::"
    else:
        bad = MALFORMED[knob.kind]
    monkeypatch.setenv(name, bad)
    with pytest.raises(InvalidParameterError) as exc:
        knobs.get(name)
    # the typed error names the knob and the offending value (loud config)
    assert name in str(exc.value) and bad in str(exc.value)
    # and it is never a bare ValueError leaking an untyped contract
    assert not type(exc.value) is ValueError  # noqa: E721


def test_registry_shape_is_sound():
    assert len(ALL_KNOBS) == len(set(ALL_KNOBS))
    for name in ALL_KNOBS:
        knob = knobs.REGISTRY[name]
        assert name.startswith(knobs.PREFIX)
        assert knob.kind in ("int", "float", "bool", "str")
        assert knob.doc, f"{name} has no doc"
        if knob.choices:
            assert knob.kind == "str"
            if knob.default is not None:
                assert str(knob.default) in knob.choices, name


def test_docs_knob_table_matches_registry():
    """The committed docs/details.md knob table IS the registry rendering —
    regenerating must be a no-op (python programs/gen_api_docs.py)."""
    import gen_api_docs as g

    text = (ROOT / "docs" / "details.md").read_text()
    begin = text.index(g.KNOB_TABLE_BEGIN) + len(g.KNOB_TABLE_BEGIN)
    end = text.index(g.KNOB_TABLE_END)
    committed = text[begin:end].strip()
    assert committed == g.knob_table().strip()
    # every non-internal registered knob has exactly one table row
    rows = [l for l in committed.splitlines() if l.startswith("| `SPFFT_TPU_")]
    assert len(rows) == len(knobs.names(internal=False))
    first_cells = [r.split("|")[1].strip().strip("`") for r in rows]
    assert sorted(first_cells) == list(knobs.names(internal=False))


def test_docs_metric_table_matches_vocabulary():
    """The committed metric table IS the obs.metrics vocabulary rendering
    (the same regeneration contract as the knob table)."""
    import gen_api_docs as g
    from spfft_tpu.obs import metrics

    text = (ROOT / "docs" / "details.md").read_text()
    begin = text.index(g.METRIC_TABLE_BEGIN) + len(g.METRIC_TABLE_BEGIN)
    end = text.index(g.METRIC_TABLE_END)
    committed = text[begin:end].strip()
    assert committed == g.metric_table().strip()
    rows = [l for l in committed.splitlines() if l.startswith("| `")]
    assert len(rows) == len(metrics.METRICS)
