"""Multi-host (multi-process) distributed transforms via subprocess ranks.

The analogue of the reference running its MPI tests under ``mpirun -n 2``
(reference: .github/workflows/ci.yml:80-84): N OS processes, one CPU device
each, a global N-device mesh, collectives over Gloo. Each rank supplies and
receives only its own shard's data (programs/multihost_smoke.py). The
4-process cells exceed the reference's 2-rank CI bar and exercise the
per-process block-assembly paths (parallel/execution.py pad_values /
unpad_space) beyond the minimal case, on both engines and all three exchange
disciplines.
"""
import subprocess
import sys
from pathlib import Path

import pytest

SCRIPT = Path(__file__).resolve().parent.parent / "programs" / "multihost_smoke.py"


def _multiprocess_cpu_supported() -> bool:
    """jax < 0.5 cannot run these at all: device_put onto a multi-process
    sharding routes through a collective the CPU backend rejects with
    "Multiprocess computations aren't implemented on the CPU backend"."""
    import jax

    version = tuple(int(x) for x in jax.__version__.split(".")[:2])
    return version >= (0, 5)


pytestmark = pytest.mark.skipif(
    not _multiprocess_cpu_supported(),
    reason="multi-process CPU collectives unsupported on this jax runtime",
)


def _run_ranks(nprocs, port, engine, ttype, exchange, timeout=300, overlap=1):
    env = {"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/root"}
    procs = [
        subprocess.Popen(
            [
                sys.executable, str(SCRIPT), str(rank), str(port), engine,
                ttype, exchange, str(nprocs), str(overlap),
            ],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for rank in range(nprocs)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:  # a hung rank must not leak Gloo processes / the port
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert f"RANK {rank} PASS" in out


@pytest.mark.parametrize(
    "engine,ttype,port,exchange",
    [
        ("xla", "c2c", 12971, "buffered"),
        ("mxu", "c2c", 12973, "buffered"),
        ("mxu", "r2c", 12975, "buffered"),
        # exact-counts ppermute chain over the cross-process (Gloo) mesh
        ("xla", "c2c", 12977, "compact"),
        ("mxu", "c2c", 12979, "compact"),
    ],
)
def test_two_process_roundtrip(engine, ttype, port, exchange):
    _run_ranks(2, port, engine, ttype, exchange)


@pytest.mark.parametrize(
    "engine,ttype,port,exchange",
    [
        ("xla", "c2c", 12981, "buffered"),
        ("xla", "c2c", 12983, "compact"),
        ("mxu", "c2c", 12985, "buffered"),
        ("mxu", "c2c", 12987, "compact"),
        # one-shot UNBUFFERED layout over the cross-process mesh (chain
        # transport on the Gloo CPU backend)
        ("mxu", "c2c", 12989, "unbuffered"),
        ("mxu", "r2c", 12991, "buffered"),
    ],
)
def test_four_process_roundtrip(engine, ttype, port, exchange):
    _run_ranks(4, port, engine, ttype, exchange)


@pytest.mark.parametrize(
    "engine,port,overlap",
    [
        # the OVERLAPPED rewrite under REAL cross-process collectives: the
        # padded exchange splits into chunked double-buffered Gloo
        # collectives pipelined against neighbor FFTs (PR 7's discipline,
        # until now only exercised single-controller)
        ("xla", 12993, 2),
        ("mxu", 12995, 2),
    ],
)
def test_two_process_overlapped_roundtrip(engine, port, overlap):
    _run_ranks(2, port, engine, "c2c", "buffered", overlap=overlap)
