"""Multi-host (2-process) distributed transforms via subprocess ranks.

The analogue of the reference running its MPI tests under ``mpirun -n 2``
(reference: .github/workflows/ci.yml:80-84): two OS processes, one CPU device
each, a global 2-device mesh, collectives over Gloo. Each rank supplies and
receives only its own shard's data (programs/multihost_smoke.py).
"""
import subprocess
import sys
from pathlib import Path

import pytest

SCRIPT = Path(__file__).resolve().parent.parent / "programs" / "multihost_smoke.py"


@pytest.mark.parametrize(
    "engine,ttype,port,exchange",
    [
        ("xla", "c2c", 12971, "buffered"),
        ("mxu", "c2c", 12973, "buffered"),
        ("mxu", "r2c", 12975, "buffered"),
        # exact-counts ppermute chain over the cross-process (Gloo) mesh
        ("xla", "c2c", 12977, "compact"),
        ("mxu", "c2c", 12979, "compact"),
    ],
)
def test_two_process_roundtrip(engine, ttype, port, exchange):
    env = {"PATH": "/usr/bin:/bin:/usr/local/bin", "HOME": "/root"}
    procs = [
        subprocess.Popen(
            [sys.executable, str(SCRIPT), str(rank), str(port), engine, ttype, exchange],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:  # a hung rank must not leak Gloo processes / the port
            if p.poll() is None:
                p.kill()
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-2000:]}"
        assert f"RANK {rank} PASS" in out
