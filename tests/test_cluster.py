"""Multi-host serving: RPC transport, heartbeat/host-lost ladder, chaos kill.

The robustness suite of the cross-host serving layer
(`spfft_tpu.serve.rpc` / `spfft_tpu.serve.cluster` + the scheduler's
``host_lost`` rung): wire-protocol round trips with typed error
marshalling, the executor's host-loss requeue ladder on fake plans, the
cluster front against stub RPC workers with the ``rpc.submit`` /
``host.heartbeat`` fault sites armed, and the real thing — a SIGKILLed
worker process mid-burst, every ticket resolving typed, survivors serving.
"""
from __future__ import annotations

import time

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import TransformType, faults, hostmesh, obs, sched, verify
from spfft_tpu.errors import (
    DeadlineExceededError,
    GenericError,
    HostExecutionError,
    HostLostError,
    InvalidParameterError,
    ServiceOverloadError,
)
from spfft_tpu.obs import fleet, trace
from spfft_tpu.serve import cluster, queue, rpc
from spfft_tpu.serve.cluster import ClusterFront
from spfft_tpu.serve.rpc import RpcClient, RpcServer

CLUSTER_ENV_KNOBS = (
    "SPFFT_TPU_HOSTS_HEARTBEAT_S",
    "SPFFT_TPU_HOSTS_HEARTBEAT_MISSES",
    "SPFFT_TPU_HOSTS_RETRIES",
    "SPFFT_TPU_HOSTS_BACKOFF_S",
    "SPFFT_TPU_RPC_TIMEOUT_S",
    "SPFFT_TPU_SERVE_QUEUE_CAP",
    "SPFFT_TPU_SERVE_BATCH_MAX",
    "SPFFT_TPU_SERVE_RETRIES",
)


@pytest.fixture(autouse=True)
def clean_cluster(monkeypatch):
    faults.disarm()
    faults.reseed(0)
    verify.breaker.reset()
    obs.enable()
    obs.clear()
    for knob in CLUSTER_ENV_KNOBS:
        monkeypatch.delenv(knob, raising=False)
    yield
    faults.disarm()
    verify.breaker.reset()


def _counter(name_prefix: str) -> int:
    return sum(
        v for k, v in obs.snapshot().get("counters", {}).items()
        if k.startswith(name_prefix)
    )


# ---- wire protocol ----------------------------------------------------------


def test_wire_array_roundtrip():
    for a in (
        np.arange(12, dtype=np.int32).reshape(4, 3),
        np.linspace(0, 1, 7, dtype=np.float32),
        (np.arange(6) + 1j * np.arange(6)).astype(np.complex128),
    ):
        out = rpc.decode_value(rpc.encode_value({"x": [a, {"y": a}]}))
        np.testing.assert_array_equal(out["x"][0], a)
        np.testing.assert_array_equal(out["x"][1]["y"], a)
        assert out["x"][0].dtype == a.dtype


def test_wire_error_payload_roundtrips_taxonomy():
    for exc in (
        ServiceOverloadError("queue full"),
        DeadlineExceededError("too late"),
        HostLostError("host died"),
        InvalidParameterError("bad dims"),
    ):
        payload = rpc.error_payload(exc)["error"]
        with pytest.raises(type(exc), match=str(exc)):
            rpc.raise_error_payload(payload)


def test_rpc_client_malformed_address_typed():
    with pytest.raises(InvalidParameterError):
        RpcClient("nonsense")
    with pytest.raises(InvalidParameterError):
        RpcClient("host:notaport")


def test_rpc_client_unreachable_is_host_lost():
    client = RpcClient("127.0.0.1:9", timeout_s=0.5)  # discard port: refused
    with pytest.raises(HostLostError, match="unreachable"):
        client.call({"op": "ping"})
    client.close()


# ---- stub worker (a real RpcServer around a fake service) -------------------


class _StubTicket:
    def __init__(self, value):
        self._value = value

    def result(self, timeout=None):
        if isinstance(self._value, BaseException):
            raise self._value
        return self._value


class _StubQueue:
    def depth(self):
        return 0


class _StubService:
    """Echo service: backward doubles the payload (no jax, no plans)."""

    def __init__(self, fail_with=None, fail_submits=()):
        self.queue = _StubQueue()
        self.fail_with = fail_with
        self.fail_submits = set(fail_submits)  # 0-based submit ordinals
        self.submitted = 0

    def submit(self, transform_type, dims, indices, payload, *,
               direction="backward", tenant="default", timeout_s=None,
               scaling=None, run_id=None):
        ordinal = self.submitted
        self.submitted += 1
        if self.fail_with is not None:
            raise self.fail_with
        if ordinal in self.fail_submits:
            raise ServiceOverloadError(f"stub refused submit {ordinal}")
        return _StubTicket(np.asarray(payload) * 2)

    def stats(self):
        return {"queue_capacity": 0}

    def describe(self):
        return {"stub": True}


@pytest.fixture()
def stub_worker():
    service = _StubService()
    server = RpcServer(service, port=0, timeout_s=10.0)
    yield service, server
    server.close()


def test_rpc_server_unknown_op_typed(stub_worker):
    _, server = stub_worker
    client = RpcClient(server.address, timeout_s=5.0)
    try:
        with pytest.raises(InvalidParameterError, match="unknown RPC op"):
            client.call({"op": "bogus"})
    finally:
        client.close()


def test_rpc_server_submit_and_batch(stub_worker):
    _, server = stub_worker
    client = RpcClient(server.address, timeout_s=5.0)
    vals = np.arange(5, dtype=np.float64)
    msg = {
        "op": "submit", "transform_type": 0, "dims": [4, 4, 4],
        "indices": np.zeros((5, 3), np.int32), "payload": vals,
    }
    try:
        np.testing.assert_array_equal(client.call(msg)["result"], vals * 2)
        out = client.call(
            {**msg, "op": "submit_batch", "payloads": [vals, vals + 1]}
        )
        np.testing.assert_array_equal(out["results"][0]["result"], vals * 2)
        np.testing.assert_array_equal(
            out["results"][1]["result"], (vals + 1) * 2
        )
    finally:
        client.close()


def test_rpc_idle_pooled_connection_stays_usable():
    """The server must NOT drop idle connections on its recv timeout: the
    client pool holds sockets across bursts, and an idle-dropped socket's
    next use would read as host death — ejecting a healthy host."""
    service = _StubService()
    server = RpcServer(service, port=0, timeout_s=0.3)
    client = RpcClient(server.address, timeout_s=5.0)
    try:
        assert client.call({"op": "ping"})["ok"] == 1
        time.sleep(1.0)  # > 3 server-side recv timeouts of idleness
        # the SAME pooled socket must still answer
        assert client.call({"op": "ping"})["ok"] == 1
    finally:
        client.close()
        server.close()


def test_rpc_oversized_reply_is_typed_not_host_loss(stub_worker, monkeypatch):
    """A reply breaching the frame cap answers with the typed error instead
    of dying: a silent drop reads as host loss and would requeue the same
    doomed batch onto every host in turn."""

    class _BigStub(_StubService):
        def submit(self, *a, **kw):
            self.submitted += 1
            return _StubTicket(np.zeros(100_000))

    service = _BigStub()
    server = RpcServer(service, port=0, timeout_s=5.0)
    # cap between the small request frame and the ~1.3 MB reply frame
    monkeypatch.setattr(rpc, "MAX_FRAME_BYTES", 50_000)
    client = RpcClient(server.address, timeout_s=5.0)
    try:
        with pytest.raises(InvalidParameterError, match="frame"):
            client.call({
                "op": "submit", "transform_type": 0, "dims": [4, 4, 4],
                "indices": np.zeros((1, 3), np.int32), "payload": np.zeros(1),
            })
        # typed, not host loss: the connection (and the host) stay usable
        assert client.call({"op": "ping"})["ok"] == 1
    finally:
        client.close()
        server.close()


def test_rpc_server_application_error_crosses_typed(stub_worker):
    service, server = stub_worker
    service.fail_with = ServiceOverloadError("stub is full")
    client = RpcClient(server.address, timeout_s=5.0)
    try:
        with pytest.raises(ServiceOverloadError, match="stub is full"):
            client.call({
                "op": "submit", "transform_type": 0, "dims": [4, 4, 4],
                "indices": np.zeros((1, 3), np.int32),
                "payload": np.zeros(1),
            })
        # an application error is NOT host loss: the transport stays usable
        assert client.call({"op": "ping"})["ok"] == 1
    finally:
        client.close()


# ---- executor host_lost ladder (fake plans, no RPC) -------------------------


class _FakePending:
    def is_ready(self):
        return True


class _LostPlan:
    """Dispatch raises HostLostError while ``lost``; ``rehost`` heals it."""

    _verifier = None
    _guard = False
    device = None

    def __init__(self, lost=True, can_rehost=True, lose_finalize=0):
        self.lost = lost
        self.can_rehost = can_rehost
        self.lose_finalize = lose_finalize
        self.rehosts = 0

    def rehost(self, error):
        if not self.can_rehost:
            raise HostLostError("no live worker hosts remain")
        self.rehosts += 1
        self.lost = False

    def _dispatch_backward(self, payload):
        if self.lost:
            raise HostLostError("host died at dispatch")
        return _FakePending()

    def _finalize_backward(self, pending):
        if self.lose_finalize > 0:
            self.lose_finalize -= 1
            self.lost = True
            raise HostLostError("host died in flight")
        return "ok"


class _NoHookPlan:
    _verifier = None
    _guard = False
    device = None

    def _dispatch_backward(self, payload):
        raise HostLostError("host died; this plan cannot move")


def test_executor_rehosts_and_completes():
    plan = _LostPlan(lost=True)
    graph = sched.TaskGraph()
    tid = graph.add("backward", payload=[1.0], transform=plan)
    report = sched.run_graph(graph, retries=0, demote=False, host_retries=2)
    assert report.outcomes[tid] == "completed"
    assert report.results[tid] == "ok"
    assert plan.rehosts == 1
    assert _counter("host_requeues_total") == 1


def test_executor_finalize_host_loss_rehosts():
    """A host dying mid-flight (dispatch acked, result never arrives):
    finalize raises HostLostError, the task re-dispatches on the new
    host."""
    plan = _LostPlan(lost=False, lose_finalize=1)
    graph = sched.TaskGraph()
    tid = graph.add("backward", payload=[1.0], transform=plan)
    report = sched.run_graph(graph, retries=0, demote=False, host_retries=2)
    assert report.outcomes[tid] == "completed"
    assert plan.rehosts == 1


def test_executor_no_hook_resolves_host_lost_and_cascades():
    """A plan without a rehost hook resolves typed with the host_lost
    outcome, and dependents cascade upstream_failed — the typed cascade
    extended to host death."""
    graph = sched.TaskGraph()
    t1 = graph.add("backward", payload=[1.0], transform=_NoHookPlan())
    t2 = graph.add(
        "backward", payload=[2.0], transform=_LostPlan(lost=False),
        after=[t1],
    )
    report = sched.run_graph(graph, retries=0, demote=False, host_retries=2)
    assert report.outcomes[t1] == "host_lost"
    assert isinstance(report.errors[t1], HostLostError)
    assert report.outcomes[t2] == "upstream_failed"
    assert isinstance(report.errors[t2], HostExecutionError)
    with pytest.raises(HostLostError):
        report.result(t1)


def test_executor_no_survivors_resolves_host_lost():
    plan = _LostPlan(lost=True, can_rehost=False)
    graph = sched.TaskGraph()
    tid = graph.add("backward", payload=[1.0], transform=plan)
    report = sched.run_graph(graph, retries=0, demote=False, host_retries=3)
    assert report.outcomes[tid] == "host_lost"
    assert isinstance(report.errors[tid], HostLostError)


def test_executor_host_retry_budget_exhausts():
    class _AlwaysLost(_LostPlan):
        def rehost(self, error):
            self.rehosts += 1  # "moves", but the next host dies too

    plan = _AlwaysLost(lost=True)
    graph = sched.TaskGraph()
    tid = graph.add("backward", payload=[1.0], transform=plan)
    report = sched.run_graph(graph, retries=0, demote=False, host_retries=2)
    assert report.outcomes[tid] == "host_lost"
    assert plan.rehosts == 2  # exactly the budget, then typed resolution


# ---- cluster front against stub workers -------------------------------------


def _front(addresses, **kw):
    kw.setdefault("heartbeat_s", 5.0)  # quiet by default: tests own timing
    kw.setdefault("rpc_timeout_s", 10.0)
    return ClusterFront(addresses, **kw)


def test_front_typed_validation(stub_worker):
    _, server = stub_worker
    with pytest.raises(InvalidParameterError):
        ClusterFront([])
    front = _front([server.address], start=False)
    trip = np.zeros((4, 3), np.int32)
    with pytest.raises(InvalidParameterError, match="unknown direction"):
        front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(4),
                     direction="sideways")
    with pytest.raises(InvalidParameterError, match="dims"):
        front.submit(TransformType.C2C, (4, 4), trip, np.zeros(4))
    with pytest.raises(InvalidParameterError, match="frequency values"):
        front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(3))
    with pytest.raises(InvalidParameterError, match="indices"):
        front.submit(TransformType.C2C, (4, 4, 4), np.zeros((4, 2), np.int32),
                     np.zeros(4))
    front.close()


def test_front_roundtrip_and_describe(stub_worker):
    _, server = stub_worker
    front = _front([server.address], start=False)
    trip = np.zeros((4, 3), np.int32)
    vals = np.arange(4, dtype=np.float64)
    tk = front.submit(TransformType.C2C, (4, 4, 4), trip, vals)
    front.pump()
    np.testing.assert_array_equal(tk.result(timeout=10), vals * 2)
    d = front.describe()
    assert d["stats"]["counts"]["completed"] == 1
    assert d["hosts"][0]["lost"] is False
    assert d["plan_cards"][0]["degradations"] == []
    assert d["config"]["heartbeat_s"] == 5.0
    front.close()


def test_front_expired_deadline_refused_typed(stub_worker):
    _, server = stub_worker
    front = _front([server.address], start=False)
    trip = np.zeros((4, 3), np.int32)
    with pytest.raises(DeadlineExceededError):
        # a deadline this tight is expired by the admission check
        # microseconds later (timeout_s <= 0 means "no deadline", so the
        # smallest representable positive timeout is the expired case)
        front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(4),
                     timeout_s=1e-12)
    front.close()


def test_front_rpc_submit_chaos_resolves_typed(stub_worker):
    """The rpc.submit site armed raise at rate 1.0: every dispatch fails,
    retries exhaust, and every ticket resolves with a TYPED error — the
    no-deadlock contract under RPC machinery death."""
    _, server = stub_worker
    front = _front([server.address], start=False, retries=1, backoff_s=0.0)
    trip = np.zeros((4, 3), np.int32)
    with faults.inject("rpc.submit=raise"):
        tickets = [
            front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(4))
            for _ in range(4)
        ]
        front.pump()
    for tk in tickets:
        with pytest.raises(GenericError):
            tk.result(timeout=10)
        assert tk.outcome == "failed"
    assert _counter("faults_injected_total") > 0
    front.close()


def test_front_rpc_submit_fractional_chaos_heals(stub_worker):
    """Sub-1.0 rpc.submit chaos: the scheduler's retry ladder re-dispatches
    through the injected failures and every ticket completes."""
    _, server = stub_worker
    front = _front(
        [server.address], start=False, retries=4, backoff_s=0.0,
        batch_max=2,
    )
    trip = np.zeros((4, 3), np.int32)
    vals = np.arange(4, dtype=np.float64)
    faults.reseed(7)
    with faults.inject("rpc.submit=raise:0.3"):
        tickets = [
            front.submit(TransformType.C2C, (4, 4, 4), trip, vals + i)
            for i in range(8)
        ]
        front.pump()
    for i, tk in enumerate(tickets):
        np.testing.assert_array_equal(tk.result(timeout=10), (vals + i) * 2)
    front.close()


def test_front_heartbeat_chaos_declares_host_lost(stub_worker):
    """The host.heartbeat site armed raise: the monitor's probes fail, the
    miss budget exhausts, the host lands in hosts_lost_total — liveness
    machinery death degrades through the same typed ladder as a dead
    host."""
    _, server = stub_worker
    with faults.inject("host.heartbeat=raise"):
        front = _front(
            [server.address], start=True, heartbeat_s=0.05,
            heartbeat_misses=2,
        )
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not front.hosts[0].lost:
            time.sleep(0.02)
        assert front.hosts[0].lost
        # with every host lost, an admitted request resolves typed
        trip = np.zeros((4, 3), np.int32)
        tk = front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(4))
        with pytest.raises(HostLostError):
            tk.result(timeout=10)
        front.close()
    assert _counter("hosts_lost_total") == 1
    assert _counter("host_heartbeats_total") > 0
    d = front.describe()
    assert d["degradations"][0]["event"] == "host_lost"


def test_front_member_failure_preserves_peers():
    """One member of a coalesced chunk refused by the worker (typed): the
    refused ticket fails with ITS error, every completed peer resolves —
    per-entry replies are never collapsed into a whole-chunk failure (which
    would discard and re-execute completed remote work)."""
    service = _StubService(fail_submits={1})
    server = RpcServer(service, port=0, timeout_s=10.0)
    front = _front([server.address], start=False, retries=0, batch_max=8)
    trip = np.zeros((4, 3), np.int32)
    vals = np.arange(4, dtype=np.float64)
    try:
        tickets = [
            front.submit(TransformType.C2C, (4, 4, 4), trip, vals + i)
            for i in range(4)
        ]
        front.pump()
        for i, tk in enumerate(tickets):
            if i == 1:
                with pytest.raises(ServiceOverloadError, match="refused"):
                    tk.result(timeout=10)
            else:
                np.testing.assert_array_equal(
                    tk.result(timeout=10), (vals + i) * 2
                )
        # the worker executed each member exactly once: no chunk re-run
        assert service.submitted == 4
    finally:
        front.close()
        server.close()


def test_remote_plan_short_reply_is_host_lost(stub_worker):
    """A reply whose results list does not match the payloads sent is a
    transport-grade failure: typed HostLostError (feeding the requeue
    ladder), never silently-unresolved tail tickets."""
    _, server = stub_worker
    front = _front([server.address], start=False)
    entry = front._ensure_entry(
        TransformType.C2C, (4, 4, 4), np.zeros((4, 3), np.int32)
    )
    plan = cluster.RemotePlan(front, entry, front.hosts[0])

    class _ShortPending:
        expected = 3
        _client = front.hosts[0].client

        def result(self):
            return {"results": [{"result": np.zeros(4)}]}  # 1 of 3

    with pytest.raises(HostLostError, match="malformed"):
        plan._finalize(_ShortPending())
    front.close()


def test_front_requeues_to_surviving_stub():
    """Two stub workers; worker 0's server dies (listener + conns torn
    down) while the front dispatches — the dead transport raises
    HostLostError, the scheduler rehosts onto worker 1, every ticket
    completes, and the host_lost rung lands on the geometry card."""
    s0, server0 = _StubService(), None
    s1 = _StubService()
    server0 = RpcServer(s0, port=0, timeout_s=5.0)
    server1 = RpcServer(s1, port=0, timeout_s=5.0)
    front = _front([server0.address, server1.address], start=False,
                   retries=0)
    trip = np.zeros((4, 3), np.int32)
    vals = np.arange(4, dtype=np.float64)
    try:
        # kill worker 0 outright (close the listener; queued dials fail)
        server0.close()
        tickets = [
            front.submit(TransformType.C2C, (4, 4, 4), trip, vals + i)
            for i in range(4)
        ]
        front.pump()
        for i, tk in enumerate(tickets):
            np.testing.assert_array_equal(
                tk.result(timeout=10), (vals + i) * 2
            )
        assert front.hosts[0].lost
        assert not front.hosts[1].lost
        assert s1.submitted > 0 and s0.submitted == 0
        cards = front.describe()["plan_cards"]
        assert any(
            d["event"] == "host_lost" and d.get("rehomed_to") == "host1"
            for c in cards for d in c["degradations"]
        )
        # the fleet-level loss itself is ALSO on every geometry card (the
        # chaos-proof criterion holds even without an in-flight requeue)
        assert any(
            d["event"] == "host_lost" and "rehomed_to" not in d
            for c in cards for d in c["degradations"]
        )
        assert _counter("hosts_lost_total") == 1
    finally:
        front.close()
        server1.close()


# ---- the real thing: SIGKILLed worker process mid-burst ---------------------


def test_sigkill_worker_mid_flight_requeues_and_serves(tmp_path):
    """2 real worker processes, a burst in flight, worker 0 SIGKILLed with
    the heartbeat too slow to notice: the dead transport surfaces typed,
    in-flight chunks requeue onto the survivor, EVERY ticket resolves, the
    accounting is exact, and the host_lost rung is on cards and metrics —
    the chaos proof of the whole ladder, in-suite."""
    workers = hostmesh.spawn_workers(
        2, devices_per_host=1, workdir=str(tmp_path),
    )
    front = ClusterFront(
        [w.address for w in workers], heartbeat_s=30.0, batch_max=2,
        rpc_timeout_s=60.0,
    )
    trip = sp.create_spherical_cutoff_triplets(8, 8, 8, 0.8)
    rng = np.random.default_rng(0)
    vals = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    try:
        # warm both workers (plan build + compile) outside the chaos window
        warm = [
            front.submit(TransformType.C2C, (8, 8, 8), trip, vals * (1 + i))
            for i in range(4)
        ]
        for tk in warm:
            tk.result(timeout=120)
        tickets = [
            front.submit(TransformType.C2C, (8, 8, 8), trip, vals * (1 + i))
            for i in range(10)
        ]
        time.sleep(0.02)  # let chunks reach worker 0's wire
        workers[0].kill()
        outcomes = {"completed": 0, "failed": 0}
        for tk in tickets:
            try:
                tk.result(timeout=120)
                outcomes["completed"] += 1
            except GenericError:
                outcomes["failed"] += 1
        # every ticket resolved (typed or completed): exact accounting
        assert outcomes["completed"] + outcomes["failed"] == len(tickets)
        # the survivor kept serving: work completed after the kill
        assert outcomes["completed"] > 0
        # the burst can drain before the kill lands (warm workers, tiny
        # transforms) and the heartbeat is deliberately too slow to notice:
        # a post-kill wave of 2 chunks forces round-robin dispatch onto the
        # dead host, so discovery happens through the typed rehost ladder
        wave = [
            front.submit(TransformType.C2C, (8, 8, 8), trip, vals * (1 + i))
            for i in range(4)
        ]
        for tk in wave:
            tk.result(timeout=120)
        assert front.hosts[0].lost
        assert not front.hosts[1].lost
        assert _counter("hosts_lost_total") == 1
        # fresh submissions after the loss complete on the survivor
        tk = front.submit(TransformType.C2C, (8, 8, 8), trip, vals)
        res = tk.result(timeout=120)
        dense = np.zeros((8, 8, 8), complex)
        t = np.asarray(trip)
        dense[t[:, 2] % 8, t[:, 1] % 8, t[:, 0] % 8] = vals
        oracle = np.fft.ifftn(dense) * 512
        # workers run at their own default (f32) precision: the parity bar
        # is the f32 engine bar, not the parent conftest's x64 one
        assert np.abs(np.asarray(res) - oracle).max() < 1e-4
    finally:
        front.close()
        hostmesh.stop_workers(workers)


# ---- fleet observability (ISSUE 16) ------------------------------------------


def test_front_trace_propagation_joins_run(stub_worker):
    """The tentpole join: one front-side snapshot holds BOTH sides of a
    dispatch under the submitting request's run ID — the front's own
    events untagged, the worker's reply segment spliced back host-tagged
    with its remote timestamps preserved."""
    _, server = stub_worker
    trace.enable(capacity=4096)
    try:
        front = _front([server.address], start=False)
        trip = np.zeros((4, 3), np.int32)
        vals = np.arange(4, dtype=np.float64)
        tk = front.submit(TransformType.C2C, (4, 4, 4), trip, vals)
        front.pump()
        np.testing.assert_array_equal(tk.result(timeout=10), vals * 2)
        assert tk.run
        evs = [e for e in trace.snapshot()["events"] if e["run"] == tk.run]
        local = [e for e in evs if "host" not in e["args"]]
        spliced = [e for e in evs if "host" in e["args"]]
        assert any(
            e["name"] == "serve" and e["args"].get("what") == "admit"
            for e in local
        )
        assert spliced, evs
        assert all(e["args"]["host"] == "host0" for e in spliced)
        assert all("remote_ts" in e["args"] for e in spliced)
        assert _counter("remote_spans_spliced_total") == len(spliced)
        front.close()
    finally:
        trace.disable()


def test_front_ticket_timeline_and_phase_histograms(stub_worker):
    """A remote-served ticket's timeline reaches every wire phase in
    PHASES order, phase_seconds keys by the phase REACHED, and every
    resolution feeds the serve_phase_seconds{phase} histogram family."""
    _, server = stub_worker
    front = _front([server.address], start=False)
    trip = np.zeros((4, 3), np.int32)
    tk = front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(4))
    front.pump()
    tk.result(timeout=10)
    tl = [p["phase"] for p in tk.timeline()]
    assert tl == [p for p in queue.PHASES if p in tl]  # PHASES order
    for phase in ("admitted", "dispatched", "wire", "remote_execute",
                  "finalized"):
        assert phase in tl, (phase, tl)
    # timeline times are monotone non-decreasing, relative to submission
    ts = [p["t"] for p in tk.timeline()]
    assert ts == sorted(ts) and ts[0] >= 0.0
    ps = tk.phase_seconds()
    assert set(ps) <= set(queue.PHASES) and "admitted" not in ps
    hists = obs.snapshot()["histograms"]
    for phase in ("wire", "remote_execute", "finalized"):
        key = f'serve_phase_seconds{{phase="{phase}"}}'
        assert hists[key]["count"] >= 1, sorted(hists)
    front.close()


def test_front_chaos_closes_trace_typed_and_fleet_skips_lost(stub_worker):
    """Satellite 4: host.heartbeat + rpc.submit armed AND the host lost
    mid-request — the request's trace closes typed (error what=host_lost
    under its run ID), fleet_snapshot stamps the lost host typed without
    touching the wire, and a scrape of the dead server never blocks past
    the RPC deadline."""
    _, server = stub_worker
    trace.enable(capacity=4096)
    try:
        with faults.inject("host.heartbeat=raise,rpc.submit=raise"):
            front = _front(
                [server.address], start=True, heartbeat_s=0.05,
                heartbeat_misses=2, retries=0, backoff_s=0.0,
            )
            trip = np.zeros((4, 3), np.int32)
            tk = front.submit(TransformType.C2C, (4, 4, 4), trip,
                              np.zeros(4))
            with pytest.raises(GenericError):
                tk.result(timeout=10)
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline and not front.hosts[0].lost:
                time.sleep(0.02)
            assert front.hosts[0].lost
            # a request admitted AFTER the loss closes its trace typed
            tk2 = front.submit(TransformType.C2C, (4, 4, 4), trip,
                               np.zeros(4))
            with pytest.raises(HostLostError):
                tk2.result(timeout=10)
            evs = [
                e for e in trace.snapshot()["events"] if e["run"] == tk2.run
            ]
            assert any(
                e["name"] == "error"
                and e["args"].get("what") == "host_lost"
                for e in evs
            ), evs
            # the lost host is skipped typed: no wire touched, no hang
            t0 = time.monotonic()
            doc = front.fleet_metrics(timeout_s=0.5)
            assert time.monotonic() - t0 < 5.0
            entry = doc["hosts"]["host0"]
            assert entry["state"] == "lost" and "skipped_unix" in entry
            assert fleet.validate_fleet(doc) == []
            assert _counter("fleet_scrapes_total") == 1
            front.close()
        # a scrape of a DEAD server (not yet declared lost) is bounded by
        # the per-host deadline and stamped unreachable, never a hang
        server.close()
        class _H:
            name, lost = "host9", False
            client = RpcClient(server.address, timeout_s=0.5)
        t0 = time.monotonic()
        doc = fleet.fleet_snapshot([_H], timeout_s=0.5)
        assert time.monotonic() - t0 < 5.0
        assert doc["hosts"]["host9"]["state"] == "unreachable"
        _H.client.close()
    finally:
        trace.disable()


def test_front_describe_joins_fleet_document(stub_worker):
    _, server = stub_worker
    front = _front([server.address], start=False)
    trip = np.zeros((4, 3), np.int32)
    tk = front.submit(TransformType.C2C, (4, 4, 4), trip, np.zeros(4))
    front.pump()
    tk.result(timeout=10)
    d = front.describe()
    assert fleet.validate_fleet(d["fleet"]) == []
    assert d["fleet"]["hosts"]["host0"]["state"] == "live"
    # the worker is in-process here, so its scraped snapshot is this
    # process's registry: the submit counters come back host-labeled
    assert any(
        'host="host0"' in k for k in d["fleet"]["counters"]
    ), sorted(d["fleet"]["counters"])
    front.close()
