"""Degradation-ladder suite: every rung provable, every fallback recorded.

Covers the ladder order documented in docs/details.md "Failure model &
degradation ladder": MXU engine-compile failure -> jnp.fft engine fallback
(parity-correct, recorded), wisdom corruption -> quarantine-once, wisdom
write failure -> bounded retry with backoff then recorded degrade, trial
failure -> model policy, plus the plan-card ``degradations`` schema pinning
and the degradation metrics the obs registry must carry.
"""
import json
import os
import time
import warnings

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    errors,
    faults,
    obs,
    tuning,
)
from spfft_tpu.parameters import distribute_triplets
from spfft_tpu.tuning import wisdom as wisdom_mod
from utils import assert_close

DIM = 8


@pytest.fixture(autouse=True)
def clean(monkeypatch):
    faults.disarm()
    obs.enable()
    obs.clear()
    tuning.clear_memory()
    monkeypatch.delenv(tuning.WISDOM_ENV, raising=False)
    monkeypatch.delenv(faults.GUARD_ENV, raising=False)
    monkeypatch.setenv(tuning.TUNE_REPEATS_ENV, "1")
    monkeypatch.setenv(tuning.TUNE_WARMUP_ENV, "0")
    yield
    faults.disarm()
    tuning.clear_memory()


def _triplets():
    return sp.create_spherical_cutoff_triplets(DIM, DIM, DIM, 0.8)


def _counter(name: str) -> int:
    snap = obs.snapshot()
    return sum(v for k, v in snap["counters"].items() if k.startswith(name))


# ---- rung 1: engine fallback -------------------------------------------------


def test_local_engine_fallback_parity_and_record():
    trip = _triplets()
    rng = np.random.default_rng(0)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    expect = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip
    ).backward(values)
    with faults.inject("engine.compile=raise"):
        t = Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            engine="mxu",
        )
    assert t._engine == "xla"
    assert_close(t.backward(values), expect)
    back = t.forward(scaling=ScalingType.FULL)
    assert_close(back, values)
    card = t.report()
    assert obs.validate_plan_card(card) == []
    (entry,) = card["degradations"]
    assert entry["event"] == "engine_fallback"
    assert entry["from"] == "mxu" and entry["to"] == "xla"
    assert "InjectedFault" in entry["reason"]
    assert _counter("engine_fallbacks_total") == 1
    # the clone of a degraded plan is already on the fallback engine
    assert t.clone()._engine == "xla"


def test_distributed_engine_fallback_keeps_discipline():
    trip = _triplets()
    per_shard = distribute_triplets(trip, 2, DIM)
    with faults.inject("engine.compile=raise"):
        t = DistributedTransform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            [p.copy() for p in per_shard],
            mesh=sp.make_fft_mesh(2),
            engine="mxu",
            exchange_type=sp.ExchangeType.COMPACT_BUFFERED,
        )
    assert t._engine == "xla"
    assert t.exchange_type == sp.ExchangeType.COMPACT_BUFFERED
    assert t.report()["degradations"][0]["event"] == "engine_fallback"


def test_xla_engine_failure_has_no_rung_below():
    trip = _triplets()
    with faults.inject("engine.compile=raise"):
        # the site guards only MXU lowerings: the jnp.fft engine builds fine
        t = Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            engine="xla",
        )
    assert t._engine == "xla" and t.report()["degradations"] == []
    # but a genuinely failing exchange build on the bottom engine is typed
    per_shard = distribute_triplets(trip, 2, DIM)
    with faults.inject("exchange.build=raise"):
        with pytest.raises(errors.MPIError):
            DistributedTransform(
                ProcessingUnit.HOST,
                TransformType.C2C,
                DIM,
                DIM,
                DIM,
                [p.copy() for p in per_shard],
                mesh=sp.make_fft_mesh(2),
                engine="xla",
            )


def test_degraded_trial_never_poisons_wisdom(monkeypatch, tmp_path):
    """A trial plan that silently fell back (engine.compile dead inside the
    trial build) must become an error row — its timing measures the fallback,
    not the candidate — and the measured winner must be an honest label."""
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "w.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    trip = _triplets()
    with faults.inject("engine.compile=raise"):
        t = Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            policy="tuned",
        )
    rec = t._tuning
    # every mxu-flavored candidate failed honestly; xla measured and won
    assert rec["provenance"] == "wisdom"
    assert rec["choice"]["engine"] == "xla"
    by_label = {row["label"]: row for row in rec["trials"]}
    assert "ms" in by_label["xla"]
    # mxu-flavored = the candidates whose build hits the armed engine.compile
    # site (the xla fusion variants build fine and measure honestly)
    mxu_rows = [r for r in by_label.values() if r["engine"] == "mxu"]
    assert mxu_rows and all("error" in r for r in mxu_rows)
    assert all(r["error"].startswith("TrialDegradedError") for r in mxu_rows)
    # the persisted store carries the honest choice, not a mislabeled mxu
    stored = tuning.WisdomStore(str(tmp_path / "w.json"))._load()
    (entry,) = stored.values()
    assert entry["choice"]["engine"] == "xla"


def test_trial_plans_do_not_leak_degradations(monkeypatch, tmp_path):
    """Fallbacks inside tuning-trial plan builds stay on the trial plan's
    sink — the outer plan's card records only its own rungs."""
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "w.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    trip = _triplets()
    t = Transform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        DIM,
        DIM,
        DIM,
        indices=trip,
        policy="tuned",
    )
    assert t._tuning["provenance"] == "wisdom"
    assert t.report()["degradations"] == []


# ---- rung 2: wisdom quarantine + save retry ---------------------------------


def test_corrupt_wisdom_is_quarantined_once(monkeypatch, tmp_path):
    path = tmp_path / "wisdom.json"
    path.write_text("{definitely not json")
    monkeypatch.setenv(tuning.WISDOM_ENV, str(path))
    store = tuning.WisdomStore(str(path))
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        assert store.lookup({"k": 1}) is None
    assert store.fallback_reason and "corrupt" in store.fallback_reason
    # renamed, not re-parsed: original gone, *.corrupt holds the bad bytes
    assert not path.exists()
    quarantined = tmp_path / "wisdom.json.corrupt"
    assert quarantined.read_text() == "{definitely not json"
    assert _counter("wisdom_quarantined_total") == 1
    warned = [w for w in caught if "quarantined" in str(w.message)]
    assert len(warned) == 1
    # subsequent constructions see a missing (not corrupt) store: no reparse,
    # no second warning, no second quarantine
    with warnings.catch_warnings(record=True) as caught2:
        warnings.simplefilter("always")
        assert tuning.WisdomStore(str(path)).lookup({"k": 1}) is None
    assert [w for w in caught2 if "quarantined" in str(w.message)] == []
    assert _counter("wisdom_quarantined_total") == 1
    # re-measuring writes a fresh healthy store at the original path
    store.record({"k": 1}, tuning.make_entry({"k": 1}, {"engine": "xla"}, []))
    assert json.loads(path.read_text())["schema"] == tuning.WISDOM_SCHEMA


def test_quarantine_during_plan_construction(monkeypatch, tmp_path):
    path = tmp_path / "wisdom.json"
    path.write_text("{broken")
    monkeypatch.setenv(tuning.WISDOM_ENV, str(path))
    trip = _triplets()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        t = Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            policy="tuned",
        )
    assert t._tuning["provenance"] == "model"
    assert "corrupt" in t._tuning["reason"]
    assert (tmp_path / "wisdom.json.corrupt").exists()
    assert [w for w in caught if "quarantined" in str(w.message)]
    # the quarantine rung landed on the plan's own degradations section
    events = [d["event"] for d in t.report()["degradations"]]
    assert "wisdom_quarantined" in events


def test_wisdom_save_retries_with_backoff(monkeypatch, tmp_path):
    path = tmp_path / "wisdom.json"
    store = tuning.WisdomStore(str(path))
    entry = tuning.make_entry({"k": 2}, {"engine": "xla"}, [])
    sleeps = []
    monkeypatch.setattr(time, "sleep", lambda s: sleeps.append(s))
    with faults.inject("wisdom.save=raise"):
        store.record({"k": 2}, entry)  # must NOT raise
    assert not path.exists()
    assert _counter("wisdom_retries_total") == wisdom_mod.WISDOM_SAVE_ATTEMPTS
    assert _counter("wisdom_save_failures_total") == 1
    # exponential backoff between attempts (not after the last)
    base = wisdom_mod.WISDOM_SAVE_BACKOFF_S
    assert sleeps == [base, 2 * base]
    # transient failure: one loss does not poison later saves
    store.record({"k": 2}, entry)
    assert tuning.WisdomStore(str(path)).lookup({"k": 2})["choice"] == {
        "engine": "xla"
    }


def test_wisdom_save_failure_recorded_on_plan(monkeypatch, tmp_path):
    monkeypatch.setenv(tuning.WISDOM_ENV, str(tmp_path / "w.json"))
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    monkeypatch.setattr(time, "sleep", lambda s: None)
    trip = _triplets()
    with faults.inject("wisdom.save=raise"):
        t = Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            policy="tuned",
        )
    # the measured choice survives; only persistence was lost — and recorded
    assert t._tuning["provenance"] == "wisdom"
    events = [d["event"] for d in t.report()["degradations"]]
    assert "wisdom_save_failed" in events
    assert not (tmp_path / "w.json").exists()
    assert obs.validate_plan_card(t.report()) == []


def test_empty_exception_message_never_crashes_load(monkeypatch, tmp_path):
    """A bare OSError() (empty str) from the filesystem must degrade, not
    IndexError out of plan construction (faults.summarize guards it)."""
    path = tmp_path / "w.json"
    path.write_text("{}")

    def broken_open(*a, **k):
        raise OSError()

    monkeypatch.setattr("builtins.open", broken_open)
    store = tuning.WisdomStore(str(path))
    assert store.lookup({"k": 1}) is None
    assert store.fallback_reason == "corrupt wisdom file: OSError: "


def test_lockfile_failure_degrades_not_raises(monkeypatch, tmp_path):
    """An OSError from lockfile acquisition (read-only dir, ENOLCK on NFS)
    rides the same retry/degrade path as a failing write — record() never
    raises out of plan construction."""
    import contextlib

    @contextlib.contextmanager
    def broken_lock(path):
        raise OSError("ENOLCK: no locks available")
        yield  # pragma: no cover

    monkeypatch.setattr(wisdom_mod, "_file_lock", broken_lock)
    monkeypatch.setattr(time, "sleep", lambda s: None)
    store = tuning.WisdomStore(str(tmp_path / "w.json"))
    store.record({"k": 9}, tuning.make_entry({"k": 9}, {"engine": "xla"}, []))
    assert not (tmp_path / "w.json").exists()
    assert _counter("wisdom_retries_total") == wisdom_mod.WISDOM_SAVE_ATTEMPTS
    assert _counter("wisdom_save_failures_total") == 1


def test_async_synchronize_failure_is_typed():
    """ASYNCHRONOUS-mode plans fence only in synchronize(): a fence failure
    there must surface as the typed execution error, like in-transform waits."""
    trip = _triplets()
    rng = np.random.default_rng(5)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip
    )
    t.set_execution_mode(sp.ExecType.ASYNCHRONOUS)
    t.backward(values)
    with faults.inject("sync.fence=raise"):
        with pytest.raises(errors.HostExecutionError):
            t.synchronize()


def test_wisdom_corrupt_injection_quarantines(monkeypatch, tmp_path):
    """The wisdom.load corrupt kind mangles the in-memory text: the parser
    must reject it and the quarantine rung must fire — chaos-proof that a
    half-written store can never wedge plan construction."""
    path = tmp_path / "wisdom.json"
    store = tuning.WisdomStore(str(path))
    store.record({"k": 3}, tuning.make_entry({"k": 3}, {"engine": "xla"}, []))
    with faults.inject("wisdom.load=corrupt"):
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            assert store.lookup({"k": 3}) is None
    assert "corrupt" in store.fallback_reason
    assert (tmp_path / "wisdom.json.corrupt").exists()


# ---- rung 3/4 metrics + card schema -----------------------------------------


def test_degradations_section_always_present():
    trip = _triplets()
    card = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip
    ).report()
    assert card["degradations"] == []
    assert obs.validate_plan_card(card) == []
    # schema pin: a malformed entry is a validation finding
    bad = dict(card, degradations=[{"event": "x"}])
    assert "degradations[0].reason" in obs.validate_plan_card(bad)
    missing = dict(card)
    del missing["degradations"]
    assert "degradations" in obs.validate_plan_card(missing)


def test_degradation_metrics_snapshot_roundtrip():
    trip = _triplets()
    with faults.inject("engine.compile=raise"):
        Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            engine="mxu",
        )
    snap = obs.snapshot()
    assert obs.validate_snapshot(snap) == []
    text = obs.prometheus_text(snap)
    assert "spfft_tpu_engine_fallbacks_total" in text
    assert "spfft_tpu_degradations_total" in text
    assert "spfft_tpu_faults_injected_total" in text


def test_narrowed_trial_isolation_counts(monkeypatch, tmp_path):
    """The narrowed TRIAL_ERRORS still isolates engine-layer failures (typed,
    runtime, missing-lowering) but programming errors propagate."""
    from spfft_tpu.tuning import runner

    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    calls = {"n": 0}

    def flaky(transform):
        calls["n"] += 1
        raise errors.GPUSupportError("no accelerator for this candidate")

    monkeypatch.setattr(runner, "measure_candidate", flaky)
    trip = _triplets()
    t = Transform(
        ProcessingUnit.HOST,
        TransformType.C2C,
        DIM,
        DIM,
        DIM,
        indices=trip,
        policy="tuned",
    )
    assert t._tuning["provenance"] == "model"
    assert calls["n"] >= 3
    assert _counter("tuning_trial_failures_total") == calls["n"]
    assert all(
        row["error"].startswith("GPUSupportError") for row in t._tuning["trials"]
    )

    def buggy(transform):
        raise AttributeError("a bug, not a fault")

    monkeypatch.setattr(runner, "measure_candidate", buggy)
    tuning.clear_memory()
    with pytest.raises(AttributeError):
        Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            policy="tuned",
        )


def test_sync_probe_failure_is_counted(monkeypatch):
    """The narrowed sync.py handler counts swallowed probe failures."""
    from spfft_tpu import sync

    class Leaf:
        def devices(self):
            raise RuntimeError("backend torn down")

    assert sync._on_advisory_platform(Leaf()) is False
    assert _counter("sync_probe_failures_total") == 1


def test_memory_store_unaffected_by_io_faults(monkeypatch):
    monkeypatch.setenv(tuning.TUNE_CPU_ENV, "1")
    trip = _triplets()
    with faults.inject("wisdom.load=raise,wisdom.save=raise"):
        t = Transform(
            ProcessingUnit.HOST,
            TransformType.C2C,
            DIM,
            DIM,
            DIM,
            indices=trip,
            policy="tuned",
        )
    # the process-memory store does no file I/O: measured wisdom, no losses
    assert t._tuning["provenance"] == "wisdom"
    assert t.report()["degradations"] == []
    assert os.environ.get(tuning.WISDOM_ENV) is None
