"""Build + run the native C/C++ API test executable.

The native library (native/) is the C/C++/Fortran-facing runtime layer over
the XLA core — the analogue of the reference's installed library surface
(reference: include/spfft/*.h, src/spfft/*.cpp). This test drives the same
flow as the reference's C example (reference: examples/example.c) through
the compiled library to prove the full C ABI works, including error codes.
"""
import os
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
BUILD = NATIVE / "build"

needs_toolchain = pytest.mark.skipif(
    shutil.which("cmake") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)


def _build_native():
    generator = ["-G", "Ninja"] if shutil.which("ninja") else []
    if not (BUILD / "CMakeCache.txt").exists():
        subprocess.run(
            ["cmake", "-S", str(NATIVE), "-B", str(BUILD), "-DCMAKE_BUILD_TYPE=Release"]
            + generator,
            check=True,
            capture_output=True,
        )
    subprocess.run(["cmake", "--build", str(BUILD)], check=True, capture_output=True)


def _native_env():
    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    # The embedded interpreter must not inherit the virtual-mesh test config.
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    return env


@needs_toolchain
def test_native_c_api_roundtrip():
    _build_native()
    env = _native_env()
    result = subprocess.run(
        [str(BUILD / "run_native_tests")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "ALL NATIVE TESTS PASSED" in result.stdout

    # C++-surface test: Grid copy fidelity (local / 1-D / pencil meshes)
    result = subprocess.run(
        [str(BUILD / "run_native_tests_cpp")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "ALL NATIVE C++ TESTS PASSED" in result.stdout


@needs_toolchain
def test_native_benchmark_cli():
    """The native benchmark (native/programs/benchmark.c — the rebuild of the
    reference's tests/programs/benchmark.cpp) runs the local, multi-transform
    and distributed paths through the C ABI and emits the JSON report."""
    import json

    _build_native()
    env = _native_env()
    exe = str(BUILD / "spfft_tpu_benchmark")

    out = BUILD / "bench_smoke.json"
    result = subprocess.run(
        [exe, "-d", "16", "16", "16", "-r", "2", "-s", "0.5", "-t", "r2c",
         "-m", "2", "-o", str(out)],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    report = json.loads(out.read_text())
    assert report["parameters"]["num_transforms"] == 2
    assert report["results"]["ms_per_pair"] > 0

    result = subprocess.run(
        [exe, "-d", "16", "16", "16", "-r", "2", "--shards", "2", "-e", "unbuffered"],
        env=env, capture_output=True, text=True, timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "wire_bytes=" in result.stdout
    report = json.loads(result.stdout[result.stdout.index("{"):])
    assert report["parameters"]["exchange"] == "unbuffered"

    # bad usage fails fast with a usage message, not a crash
    for bad in (["-d", "16", "16"],
                ["-d", "16", "16", "16", "-r", "2", "-t", "R2C"],
                ["-d", "16", "16", "16", "-r", "2", "-e", "bufferred"]):
        result = subprocess.run([exe] + bad, env=env,
                                capture_output=True, text=True, timeout=60)
        assert result.returncode == 2, bad
