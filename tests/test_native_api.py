"""Build + run the native C/C++ API test executable.

The native library (native/) is the C/C++/Fortran-facing runtime layer over
the XLA core — the analogue of the reference's installed library surface
(reference: include/spfft/*.h, src/spfft/*.cpp). This test drives the same
flow as the reference's C example (reference: examples/example.c) through
the compiled library to prove the full C ABI works, including error codes.
"""
import os
import shutil
import subprocess
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
NATIVE = REPO / "native"
BUILD = NATIVE / "build"


@pytest.mark.skipif(
    shutil.which("cmake") is None or shutil.which("g++") is None,
    reason="native toolchain unavailable",
)
def test_native_c_api_roundtrip():
    generator = ["-G", "Ninja"] if shutil.which("ninja") else []
    if not (BUILD / "CMakeCache.txt").exists():
        subprocess.run(
            ["cmake", "-S", str(NATIVE), "-B", str(BUILD), "-DCMAKE_BUILD_TYPE=Release"]
            + generator,
            check=True,
            capture_output=True,
        )
    subprocess.run(
        ["cmake", "--build", str(BUILD)], check=True, capture_output=True
    )

    env = dict(os.environ)
    env["PYTHONPATH"] = str(REPO) + os.pathsep + env.get("PYTHONPATH", "")
    # The embedded interpreter must not inherit the virtual-mesh test config.
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    result = subprocess.run(
        [str(BUILD / "run_native_tests")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "ALL NATIVE TESTS PASSED" in result.stdout

    # C++-surface test: Grid copy fidelity (local / 1-D / pencil meshes)
    result = subprocess.run(
        [str(BUILD / "run_native_tests_cpp")],
        env=env,
        capture_output=True,
        text=True,
        timeout=600,
    )
    assert result.returncode == 0, result.stdout + result.stderr
    assert "ALL NATIVE C++ TESTS PASSED" in result.stdout
