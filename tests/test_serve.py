"""Chaos suite for the serving layer (spfft_tpu.serve).

The acceptance invariant (ISSUE 8): at offered load beyond capacity, with
faults armed on every ``serve.*`` site, the service keeps a bounded queue,
rejects/sheds with typed errors, never deadlocks, and every accepted request
either completes (verified, when armed) or fails typed. The suite pins the
admission rules (backpressure, quota, fair share, deadlines at admission AND
pre-dispatch), same-geometry coalescing with per-caller value orders, the
plan cache, retry-with-jitter, the breaker shed-or-demote ladder, and the
obs exposure (metrics + trace + describe join).
"""
import threading
import time

import numpy as np
import pytest

import spfft_tpu as sp
from spfft_tpu import (
    ProcessingUnit,
    ScalingType,
    Transform,
    TransformType,
    errors,
    faults,
    obs,
    serve,
    verify,
)
from spfft_tpu.parallel.ragged import value_order_map
from utils import assert_close

DIM = 8
DIMS = (DIM, DIM, DIM)

SERVE_ENV_KNOBS = (
    serve.SERVE_QUEUE_CAP_ENV,
    serve.SERVE_BATCH_MAX_ENV,
    serve.SERVE_TENANT_QUOTA_ENV,
    serve.SERVE_TIMEOUT_ENV,
    serve.SERVE_RETRIES_ENV,
    serve.SERVE_BACKOFF_ENV,
    serve.SERVE_ON_BREAKER_ENV,
    serve.SERVE_PLANS_ENV,
)


@pytest.fixture(autouse=True)
def clean_serve(monkeypatch):
    """Serving state must never leak between tests: disarm faults, reset the
    process-global breaker and metrics, scrub the serve env knobs."""
    faults.disarm()
    faults.reseed(0)
    verify.breaker.reset()
    obs.enable()
    obs.clear()
    for knob in SERVE_ENV_KNOBS:
        monkeypatch.delenv(knob, raising=False)
    yield
    faults.disarm()
    verify.breaker.reset()


def _triplets(dim=DIM, frac=0.8):
    return sp.create_spherical_cutoff_triplets(dim, dim, dim, frac)


def _values(trip, seed=0):
    rng = np.random.default_rng(seed)
    return rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))


def _expect_backward(trip, values):
    t = Transform(
        ProcessingUnit.HOST, TransformType.C2C, DIM, DIM, DIM, indices=trip
    )
    return t.backward(values)


def _service(**kw):
    kw.setdefault("start", False)
    kw.setdefault("queue_capacity", 16)
    kw.setdefault("batch_max", 4)
    return serve.TransformService(**kw)


def _counter_sum(snapshot_counters, prefix):
    return sum(v for k, v in snapshot_counters.items() if k.startswith(prefix))


# ---- coalescing and parity ---------------------------------------------------


def test_coalesced_backward_parity_across_value_orders():
    """Requests sharing a stick layout but packing values in different
    orders coalesce into ONE batch and each gets its own correct result."""
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    rng = np.random.default_rng(3)
    perm = rng.permutation(len(trip))
    svc = _service()
    t1 = svc.submit(TransformType.C2C, DIMS, trip, values, tenant="a")
    t2 = svc.submit(TransformType.C2C, DIMS, trip[perm], values[perm], tenant="b")
    t3 = svc.submit(TransformType.C2C, DIMS, trip, values, tenant="a")
    assert svc.pump() == 1  # one coalesced batch, not three
    for t in (t1, t2, t3):
        assert_close(t.result(timeout=10), expect)
    snap = obs.snapshot()
    occ = snap["histograms"]["serve_batch_occupancy"]
    assert occ["count"] == 1 and occ["sum"] == 3.0
    svc.close()


def test_forward_results_return_in_caller_order():
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    rng = np.random.default_rng(4)
    perm = rng.permutation(len(trip))
    svc = _service()
    tk = svc.submit(
        TransformType.C2C, DIMS, trip[perm], expect, direction="forward",
        scaling=ScalingType.FULL,
    )
    svc.pump()
    assert_close(tk.result(timeout=10), values[perm])
    svc.close()


def test_centered_and_wrapped_indexing_share_a_plan():
    """Centered (negative-frequency) triplets and their wrapped storage form
    are the same geometry: one plan-cache entry, coalesced batches."""
    trip = _triplets()
    wrapped = serve.wrap_triplets(trip, DIMS)
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = _service()
    t1 = svc.submit(TransformType.C2C, DIMS, trip, values)
    t2 = svc.submit(TransformType.C2C, DIMS, wrapped, values)
    assert svc.pump() == 1
    assert_close(t1.result(timeout=10), expect)
    assert_close(t2.result(timeout=10), expect)
    assert svc.stats()["plan_cache_entries"] == 1
    svc.close()


def test_plan_cache_hit_miss_and_eviction_counts():
    trip_a = _triplets(frac=0.8)
    trip_b = _triplets(frac=0.5)
    values_a, values_b = _values(trip_a), _values(trip_b)
    svc = _service(plan_cache_size=1)
    svc.submit(TransformType.C2C, DIMS, trip_a, values_a)
    svc.submit(TransformType.C2C, DIMS, trip_a, values_a)
    svc.submit(TransformType.C2C, DIMS, trip_b, values_b)  # evicts trip_a
    svc.pump()
    counters = obs.snapshot()["counters"]
    assert counters['serve_plan_cache_total{event="miss"}'] == 2
    assert counters['serve_plan_cache_total{event="hit"}'] == 1
    assert counters['serve_plan_cache_total{event="evict"}'] == 1
    assert svc.stats()["plan_cache_entries"] == 1
    svc.close()


def test_distinct_geometries_do_not_coalesce():
    trip_a = _triplets(frac=0.8)
    trip_b = _triplets(frac=0.5)
    svc = _service()
    ta = svc.submit(TransformType.C2C, DIMS, trip_a, _values(trip_a))
    tb = svc.submit(TransformType.C2C, DIMS, trip_b, _values(trip_b))
    assert svc.pump() == 2  # two batches: the geometries differ
    assert ta.outcome == "completed" and tb.outcome == "completed"
    svc.close()


def test_value_order_map_identity_permutation_and_mismatch():
    trip = np.asarray(_triplets(), dtype=np.int64).reshape(-1, 3) % DIM
    ident = value_order_map(trip, trip)
    assert np.array_equal(ident, np.arange(len(trip)))
    perm = np.random.default_rng(5).permutation(len(trip))
    src = value_order_map(trip, trip[perm])
    values = _values(trip)
    assert np.allclose(values[perm][src], values)
    assert value_order_map(trip, trip[: len(trip) - 1]) is None
    other = trip.copy()
    other[0] = [(other[0][0] + 1) % DIM, other[0][1], other[0][2]]
    assert value_order_map(trip, other) is None or not np.array_equal(
        np.sort(trip.view("i8,i8,i8"), axis=0), np.sort(other.view("i8,i8,i8"), axis=0)
    )


# ---- admission: backpressure, quotas, deadlines ------------------------------


def test_bounded_queue_rejects_typed_when_full():
    trip = _triplets()
    values = _values(trip)
    svc = _service(queue_capacity=3, tenant_quota=1.0)
    for _ in range(3):
        svc.submit(TransformType.C2C, DIMS, trip, values, tenant="a")
    with pytest.raises(errors.ServiceOverloadError):
        svc.submit(TransformType.C2C, DIMS, trip, values, tenant="a")
    assert svc.queue.depth() == 3  # bounded: the refusal did not enqueue
    svc.close(drain=False)


def test_tenant_quota_rejects_before_queue_full():
    trip = _triplets()
    values = _values(trip)
    svc = _service(queue_capacity=10, tenant_quota=0.2)  # 2 slots/tenant
    svc.submit(TransformType.C2C, DIMS, trip, values, tenant="noisy")
    svc.submit(TransformType.C2C, DIMS, trip, values, tenant="noisy")
    with pytest.raises(errors.ServiceOverloadError):
        svc.submit(TransformType.C2C, DIMS, trip, values, tenant="noisy")
    # other tenants unaffected
    svc.submit(TransformType.C2C, DIMS, trip, values, tenant="quiet")
    svc.close(drain=False)


def test_fair_share_shed_protects_quiet_tenant():
    """A full queue held by one noisy tenant sheds the noisy tenant's newest
    request (typed, recorded) to admit an under-share tenant."""
    trip = _triplets()
    values = _values(trip)
    svc = _service(queue_capacity=4, tenant_quota=1.0)
    noisy = [
        svc.submit(TransformType.C2C, DIMS, trip, values, tenant="noisy")
        for _ in range(4)
    ]
    quiet = svc.submit(TransformType.C2C, DIMS, trip, values, tenant="quiet")
    assert noisy[-1].done() and noisy[-1].outcome == "shed"
    with pytest.raises(errors.ServiceOverloadError):
        noisy[-1].result(timeout=0)
    assert svc.queue.depth() == 4  # still bounded
    svc.pump()
    assert quiet.outcome == "completed"
    counters = obs.snapshot()["counters"]
    assert counters['serve_sheds_total{reason="fair_share"}'] == 1
    svc.close()


def test_expired_deadline_refused_at_admission():
    trip = _triplets()
    svc = _service()
    with pytest.raises(errors.DeadlineExceededError):
        svc.submit(
            TransformType.C2C, DIMS, trip, _values(trip), timeout_s=1e-9
        )
    svc.close()


def test_deadline_shed_pre_dispatch():
    """A request that expires while queued is shed BEFORE dispatch: its
    ticket fails typed and no device time is burned on it."""
    trip = _triplets()
    values = _values(trip)
    svc = _service()
    ok = svc.submit(TransformType.C2C, DIMS, trip, values)
    doomed = svc.submit(
        TransformType.C2C, DIMS, trip, values, timeout_s=0.005, tenant="late"
    )
    time.sleep(0.02)
    svc.pump()
    assert ok.outcome == "completed"
    assert doomed.outcome == "deadline_miss"
    with pytest.raises(errors.DeadlineExceededError):
        doomed.result(timeout=0)
    counters = obs.snapshot()["counters"]
    assert counters['serve_deadline_misses_total{tenant="late"}'] == 1
    svc.close()


# ---- retries, breaker ladder, verification -----------------------------------


def test_transient_failure_retries_with_jitter_then_completes(monkeypatch):
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    from spfft_tpu.serve import service as service_mod

    real_run_batch = service_mod.run_batch
    calls = {"n": 0}

    def flaky_run_batch(entry, requests, build_clone, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise errors.HostExecutionError("transient dispatch failure")
        return real_run_batch(entry, requests, build_clone, **kw)

    monkeypatch.setattr(service_mod, "run_batch", flaky_run_batch)
    svc = _service(retries=2, backoff_s=0.001)
    tk = svc.submit(TransformType.C2C, DIMS, trip, values)
    svc.pump()
    assert_close(tk.result(timeout=10), expect)
    assert calls["n"] == 2
    assert obs.snapshot()["counters"]["serve_retries_total"] == 1
    svc.close()


def test_retry_exhaustion_fails_typed():
    trip = _triplets()
    svc = _service(retries=1, backoff_s=0.001)
    with faults.inject("serve.dispatch=raise"):
        tk = svc.submit(TransformType.C2C, DIMS, trip, _values(trip))
        svc.pump()
    assert tk.outcome == "failed"
    with pytest.raises(errors.HostExecutionError):
        tk.result(timeout=0)
    assert obs.snapshot()["counters"]["serve_retries_total"] == 1
    svc.close()


def test_breaker_open_flips_service_to_demote():
    """A tripped verify breaker on the batch's engine reroutes requests
    through the jnp.fft reference rung — results stay correct, the demotion
    is counted, and the service never queues into the dead engine."""
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = _service(on_breaker="demote")
    warm = svc.submit(TransformType.C2C, DIMS, trip, values)
    svc.pump()
    assert_close(warm.result(timeout=10), expect)
    engine = svc.plans.describe()[0]["engine"]
    for _ in range(verify.breaker.threshold()):
        verify.breaker.record_failure(engine)
    assert verify.breaker.describe(engine)["state"] == "open"
    tk = svc.submit(TransformType.C2C, DIMS, trip, values)
    svc.pump()
    assert_close(tk.result(timeout=10), expect)
    counters = obs.snapshot()["counters"]
    assert counters[f'serve_demotions_total{{engine="{engine}"}}'] == 1
    svc.close()


def test_breaker_open_shed_mode_fails_typed():
    trip = _triplets()
    svc = _service(on_breaker="shed")
    warm = svc.submit(TransformType.C2C, DIMS, trip, _values(trip))
    svc.pump()
    warm.result(timeout=10)
    engine = svc.plans.describe()[0]["engine"]
    for _ in range(verify.breaker.threshold()):
        verify.breaker.record_failure(engine)
    tk = svc.submit(TransformType.C2C, DIMS, trip, _values(trip))
    svc.pump()
    assert tk.outcome == "shed"
    with pytest.raises(errors.ServiceOverloadError):
        tk.result(timeout=0)
    counters = obs.snapshot()["counters"]
    assert counters['serve_sheds_total{reason="breaker_open"}'] == 1
    svc.close()


def test_breaker_heals_through_serve_traffic(monkeypatch):
    """An unverified service's own successful dispatch settles a half-open
    probe: after the cooldown the dispatcher carries the probe through
    allow(), a healthy batch closes the breaker, and traffic returns to the
    primary engine — a tripped breaker never demotes forever."""
    monkeypatch.setenv(verify.breaker.BREAKER_COOLDOWN_ENV, "0")
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = _service(on_breaker="demote")
    warm = svc.submit(TransformType.C2C, DIMS, trip, values)
    svc.pump()
    assert_close(warm.result(timeout=10), expect)
    engine = svc.plans.describe()[0]["engine"]
    for _ in range(verify.breaker.threshold()):
        verify.breaker.record_failure(engine)
    assert verify.breaker.describe(engine)["state"] == "open"
    # cooldown 0: the next batch carries the half-open probe on the primary
    tk = svc.submit(TransformType.C2C, DIMS, trip, values)
    svc.pump()
    assert_close(tk.result(timeout=10), expect)
    assert verify.breaker.describe(engine)["state"] == "closed"
    counters = obs.snapshot()["counters"]
    assert "serve_demotions_total" not in str(counters) or not any(
        k.startswith("serve_demotions_total") for k in counters
    )
    svc.close()


def test_out_of_range_indices_rejected_typed():
    """A typo'd out-of-range triplet must raise typed InvalidIndicesError
    at submit — never silently alias onto the wrong frequency through the
    wrap-to-storage canonicalization."""
    trip = np.asarray(_triplets(), dtype=np.int64).reshape(-1, 3).copy()
    trip[0] = [DIM, 0, 0]  # == dim_x: out of both conventions' bounds
    svc = _service()
    with pytest.raises(errors.InvalidIndicesError):
        svc.submit(TransformType.C2C, DIMS, trip, np.zeros(len(trip)))
    svc.close()


def test_verified_service_recovers_under_corruption():
    """verify="on" service + every dispatch corrupted: requests still
    complete (recovered via the supervisor's reference rung) — the
    'every accepted request completes verified or fails typed' half of the
    acceptance invariant, exercised through the serving path."""
    import warnings

    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = _service(verify="on")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        with faults.inject("engine.execute=corrupt:1.0"):
            tk = svc.submit(TransformType.C2C, DIMS, trip, values)
            svc.pump()
            result = tk.result(timeout=30)
    assert_close(result, expect)
    counters = obs.snapshot()["counters"]
    assert _counter_sum(counters, "verify_recoveries_total") >= 1
    svc.close()


# ---- the overload chaos invariant --------------------------------------------


@pytest.mark.parametrize("site_name", ["serve.admit", "serve.batch", "serve.dispatch"])
def test_chaos_invariant_serve_sites_at_overload(site_name):
    """Arm each serve.* site at rate 1.0 and offer 4x the queue capacity:
    the queue stays bounded, every refusal is typed, every ACCEPTED ticket
    resolves (typed failure here — the site kills its stage every time), and
    the pump terminates (no deadlock)."""
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = _service(queue_capacity=4, batch_max=2, retries=1, backoff_s=0.001,
                   tenant_quota=1.0)
    accepted, rejected = [], 0
    with faults.inject(f"{site_name}=raise"):
        for i in range(16):  # 4x capacity
            try:
                accepted.append(
                    svc.submit(
                        TransformType.C2C, DIMS, trip, values,
                        tenant=f"t{i % 3}",
                    )
                )
            except errors.GenericError as e:
                assert isinstance(e, errors.ServiceOverloadError), type(e)
                rejected += 1
        assert svc.queue.high_water <= 4  # bounded under overload
        svc.pump()
    typed = 0
    for tk in accepted:
        assert tk.done(), "accepted ticket left unresolved (deadlock arm)"
        try:
            # completed is legal only with a parity-correct result (e.g.
            # the breaker tripping mid-sweep demotes to the reference rung)
            assert_close(tk.result(timeout=0), expect)
        except errors.GenericError:
            typed += 1
    if site_name == "serve.admit":
        assert rejected == 16 and not accepted
    else:
        assert rejected >= 12  # the queue bound refused the overload excess
        assert typed > 0  # the armed site really fired
    svc.close()


@pytest.mark.slow
def test_chaos_all_serve_sites_fractional_under_threaded_overload():
    """Every serve.* site armed at a fractional rate, threaded dispatcher,
    offered load far beyond capacity: no deadlock, bounded queue, every
    accepted ticket resolves completed-or-typed within the budget."""
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    faults.reseed(7)
    svc = serve.TransformService(
        queue_capacity=8, batch_max=4, retries=1, backoff_s=0.001,
    )
    accepted, rejected = [], 0
    with faults.inject(
        "serve.admit=raise:0.2,serve.batch=raise:0.2,serve.dispatch=raise:0.2"
    ):
        for i in range(64):
            try:
                accepted.append(
                    svc.submit(
                        TransformType.C2C, DIMS, trip, values,
                        tenant=f"t{i % 4}",
                    )
                )
            except errors.GenericError:
                rejected += 1
        deadline = time.time() + 60
        completed = failed = 0
        for tk in accepted:
            try:
                out = tk.result(timeout=max(0.1, deadline - time.time()))
                assert_close(out, expect)
                completed += 1
            except errors.GenericError:
                failed += 1
    assert completed + failed == len(accepted)  # every ticket resolved
    assert svc.queue.high_water <= 8
    assert completed > 0  # the service made progress through the chaos
    svc.close()


# ---- lifecycle and exposure --------------------------------------------------


def test_close_fails_pending_tickets_typed():
    trip = _triplets()
    svc = _service()
    tickets = [
        svc.submit(TransformType.C2C, DIMS, trip, _values(trip))
        for _ in range(3)
    ]
    svc.close(drain=False)
    for tk in tickets:
        assert tk.outcome == "shed"
        with pytest.raises(errors.ServiceOverloadError):
            tk.result(timeout=0)
    with pytest.raises(errors.ServiceOverloadError):
        svc.submit(TransformType.C2C, DIMS, trip, _values(trip))


def test_drain_close_completes_queued_work_threaded():
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = serve.TransformService(queue_capacity=16, batch_max=4)
    tickets = [
        svc.submit(TransformType.C2C, DIMS, trip, values) for _ in range(6)
    ]
    svc.close(drain=True)
    for tk in tickets:
        assert_close(tk.result(timeout=10), expect)


def test_describe_joins_plan_cards_and_breakers():
    trip = _triplets()
    svc = _service()
    tk = svc.submit(TransformType.C2C, DIMS, trip, _values(trip))
    svc.pump()
    tk.result(timeout=10)
    desc = svc.describe()
    assert desc["config"]["queue_capacity"] == 16
    assert len(desc["plan_cache"]) == 1
    row = desc["plan_cache"][0]
    assert row["run_id"] and row["plans"] >= 1
    assert row["engine"] in desc["breakers"]
    assert desc["breakers"][row["engine"]]["state"] == "closed"
    assert desc["stats"]["counts"]["completed"] == 1
    svc.close()


def test_submit_rejects_malformed_requests_typed():
    trip = _triplets()
    svc = _service()
    with pytest.raises(errors.InvalidParameterError):
        svc.submit(TransformType.C2C, DIMS, trip, _values(trip)[:-1])
    with pytest.raises(errors.InvalidParameterError):
        svc.submit(TransformType.C2C, DIMS, trip, _values(trip), direction="sideways")
    with pytest.raises(errors.InvalidParameterError):
        svc.submit(
            TransformType.C2C, DIMS, trip, np.zeros(7), direction="forward"
        )
    svc.close()


def test_serve_latency_histogram_and_trace_events():
    trip = _triplets()
    obs.trace.enable()
    try:
        svc = _service()
        tk = svc.submit(TransformType.C2C, DIMS, trip, _values(trip), tenant="t")
        svc.pump()
        tk.result(timeout=10)
        snap = obs.snapshot()
        hist = snap["histograms"]['serve_latency_seconds{tenant="t"}']
        assert hist["count"] == 1 and hist["sum"] > 0
        events = [
            e for e in obs.trace.snapshot()["events"] if e["name"] == "serve"
        ]
        whats = {e["args"]["what"] for e in events}
        assert {"admit", "coalesce", "dispatch", "complete"} <= whats
        svc.close()
    finally:
        obs.trace.disable()
        obs.trace.clear()


@pytest.mark.slow
def test_concurrent_submitters_threaded_service():
    """Multiple submitter threads + the dispatcher thread: results all
    correct, no lost tickets (the lock discipline of queue + cache)."""
    trip = _triplets()
    values = _values(trip)
    expect = _expect_backward(trip, values)
    svc = serve.TransformService(queue_capacity=32, batch_max=4)
    results = [None] * 4

    def submitter(slot):
        tks = [
            svc.submit(TransformType.C2C, DIMS, trip, values, tenant=f"s{slot}")
            for _ in range(4)
        ]
        results[slot] = [tk.result(timeout=30) for tk in tks]

    threads = [
        threading.Thread(target=submitter, args=(i,)) for i in range(4)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(60)
    svc.close()
    for outs in results:
        assert outs is not None
        for out in outs:
            assert_close(out, expect)


# ---- end-to-end timelines (ISSUE 16) ----------------------------------------


def test_ticket_stamps_first_wins_timeline_and_deltas():
    from spfft_tpu.serve import queue as q

    tk = q.Ticket("t0", run="r1")
    tk.stamp("admitted")
    first = tk.stamps["admitted"]
    tk.stamp("admitted")  # first-wins: a retry keeps the original time
    assert tk.stamps["admitted"] == first
    with pytest.raises(errors.InvalidParameterError, match="phase"):
        tk.stamp("teleported")  # the vocabulary stays closed
    tk.stamp("dispatched")
    assert tk.resolve(object())  # resolution stamps finalized itself
    tl = tk.timeline()
    assert [p["phase"] for p in tl] == ["admitted", "dispatched", "finalized"]
    ts = [p["t"] for p in tl]
    assert ts == sorted(ts) and ts[0] >= 0.0
    # deltas between adjacent PRESENT stamps, keyed by the phase REACHED:
    # absent wire phases never appear, admitted has no predecessor
    ps = tk.phase_seconds()
    assert set(ps) == {"dispatched", "finalized"}
    assert all(v >= 0.0 for v in ps.values())


def test_service_tickets_feed_phase_histograms_in_process():
    """In-process serving stamps admitted/coalesced/dispatched/finalized —
    never the wire phases — and every resolution feeds the
    serve_phase_seconds{phase} family."""
    svc = _service()
    trip = _triplets()
    vals = _values(trip)
    try:
        tickets = [svc.submit(TransformType.C2C, DIMS, trip, vals)
                   for _ in range(3)]
        svc.pump()
        for tk in tickets:
            tk.result(timeout=30)
            tl = [p["phase"] for p in tk.timeline()]
            for phase in ("admitted", "coalesced", "dispatched", "finalized"):
                assert phase in tl, tl
            assert "wire" not in tl and "remote_execute" not in tl
    finally:
        svc.close()
    hists = obs.snapshot()["histograms"]
    for phase in ("coalesced", "dispatched", "finalized"):
        key = f'serve_phase_seconds{{phase="{phase}"}}'
        assert hists[key]["count"] >= 3, sorted(hists)
    assert 'serve_phase_seconds{phase="wire"}' not in hists
