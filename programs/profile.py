"""Capture a jax.profiler trace of transform pairs, tagged by pipeline stage.

The TPU-side analogue of the reference's rt_graph timing tree (reference:
src/timing/rt_graph.hpp, stages tagged in src/execution/execution_host.cpp:
249-293): every engine wraps its stages in ``jax.named_scope`` using the
canonical ``spfft_tpu.obs.STAGES`` labels (the reference's stage names plus
the disambiguated sparse/blocked y-variants and the pencil engine's A/B
exchange tags — ``programs/lint.py`` enforces the list both ways), so a
captured trace reads like the reference's timing output, but with XLA fusion
boundaries and DMA activity visible.

Timing rides the ONE shared discipline (``spfft_tpu.obs.perf``): warmup +
best-of-R fenced chained roundtrips (``measure_pair_seconds`` — the same
rules as ``tuning/runner.py``, ``bench.py`` and ``programs/dbench.py``), and
the per-stage breakdown printed below is the perf layer's attributed report
(``perf_report``, schema ``spfft_tpu.obs.perf/1``) — not a second ad-hoc
stage-timer path. The host timing tree (layer 1) still prints as the
portable fallback.

Usage:
    python programs/profile.py -d 128 128 128 -s 0.15 --engine mxu -r 5 \
        -o /tmp/spfft_trace

View the result with TensorBoard (`tensorboard --logdir /tmp/spfft_trace`,
Profile tab) or open the per-run `*.trace.json.gz` under
`<outdir>/plugins/profile/` in Perfetto (ui.perfetto.dev). On backends where
device trace collection is unsupported (e.g. tunneled devices), the capture
degrades to host-side python/XLA events — the host timing tree
(spfft_tpu.timing) and the attributed perf report stay the portable fallback
and are printed either way.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-d", nargs=3, type=int, default=[128, 128, 128],
                    metavar=("X", "Y", "Z"))
    ap.add_argument("-s", type=float, default=0.15, help="nonzero fraction")
    ap.add_argument("-r", type=int, default=5, help="traced roundtrips")
    ap.add_argument("--repeats", type=int, default=3,
                    help="timed best-of repeats (perf report)")
    ap.add_argument("--chain", type=int, default=2,
                    help="chained roundtrips per timed dispatch")
    ap.add_argument("--engine", default="auto", choices=["auto", "xla", "mxu"])
    ap.add_argument("-o", default="/tmp/spfft_trace", help="trace output dir")
    args = ap.parse_args(argv)

    if args.r < 1:
        ap.error("-r must be >= 1")

    import jax
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, ScalingType, TransformType, obs, timing

    timing.enable()
    dx, dy, dz = args.d
    radius = sp.spherical_radius_for_fraction(args.s)
    if radius > 1.0:
        print(f"note: -s {args.s} exceeds the inscribed ball (pi/6); clipping")
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, radius)
    with timing.scoped("Grid + Transform init"):
        t = sp.Transform(
            ProcessingUnit.GPU, TransformType.C2C, dx, dy, dz,
            indices=trip, dtype=np.float32, engine=args.engine,
        )

    # The shared timing discipline (module docstring): warmup absorbs
    # compilation, best-of-R fenced chained roundtrips, then the measured
    # pair time attributed over the canonical stages.
    measured = obs.perf.measure_pair_seconds(
        t, chain=args.chain, repeats=args.repeats
    )
    report = obs.perf.perf_report(
        t, measured["seconds_per_pair"], repeats=measured["repeats"]
    )

    rng = np.random.default_rng(0)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))

    # warm-up the jitted backward/forward entry points OUTSIDE the capture:
    # measure_pair_seconds compiled its own scan-chained program, not these,
    # so without this the first traced roundtrip would record compilation
    # instead of steady-state steps
    with timing.scoped("warmup"):
        t.backward(values)
        t.forward(scaling=ScalingType.FULL)
        t.synchronize()

    try:
        jax.profiler.start_trace(args.o)
        capture = True
    except Exception as e:  # tunneled/experimental backends may refuse capture
        print(f"device trace capture unavailable on this backend: {e}")
        print("host timing tree + perf report below are the fallback.")
        capture = False
    try:
        with timing.scoped("traced roundtrips"):
            for _ in range(args.r):
                t.backward(values)
                out = t.forward(scaling=ScalingType.FULL)
            t.synchronize()
            np.asarray(out)  # fetch fences the tail
    finally:
        if capture:
            jax.profiler.stop_trace()
            print(f"trace written to {args.o}")
            print(f"  view: tensorboard --logdir {args.o}  (Profile tab)")
            print(f"  or open {args.o}/plugins/profile/*/…trace.json.gz in Perfetto")
            # the canonical scope vocabulary to search for in the trace
            print(f"  stage scopes (spfft_tpu.obs.STAGES): {', '.join(sp.obs.STAGES)}")

    print()
    print(f"perf report (spfft_tpu.obs.perf/1, best of {args.repeats} x "
          f"chain {measured['chain']}): "
          f"{report['seconds_per_pair'] * 1e3:.3f} ms/pair, "
          f"{report['gflops']:.2f} GFLOP/s")
    for row in report["stages"]:
        print(f"  {row['stage']:<22s} {row['seconds'] * 1e6:12.1f} us "
              f"{row['fraction'] * 100:6.2f}%  "
              f"{row['gflops']:10.2f} GFLOP/s {row['gbps']:8.2f} GB/s")
    print(json.dumps(report))
    print()
    print(timing.process())


if __name__ == "__main__":
    main()
