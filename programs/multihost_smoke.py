"""Multi-process multi-host smoke run: one rank of a distributed transform.

Each process owns one CPU device of an N-device global mesh (collectives ride
Gloo across processes — the CPU stand-in for ICI/DCN, the analogue of the
reference's `mpirun -n 2` CI; N=4 exceeds that bar). All ranks build the same
seeded global plan,
supply values for their OWN shard only, run backward+forward through the mesh
engine, and verify their local slab against a dense oracle plus the value
roundtrip. Prints "RANK <r> PASS" on success.

Usage: multihost_smoke.py <rank> <port> <engine> [c2c|r2c]
       [buffered|compact|unbuffered] [nprocs] [overlap_chunks]

``overlap_chunks > 1`` applies the OVERLAPPED exchange rewrite (PR 7 /
the IR graph rewrite) across REAL process boundaries: the padded exchange
splits into chunked double-buffered cross-process collectives — the parity
assertions below prove the chunked wire protocol agrees with the dense
oracle under Gloo exactly as it does on a single-controller mesh.
"""
import os
import sys

rank = int(sys.argv[1])
port = int(sys.argv[2])
engine = sys.argv[3]
ttype_name = sys.argv[4] if len(sys.argv) > 4 else "c2c"
exchange_name = sys.argv[5] if len(sys.argv) > 5 else "buffered"
nprocs = int(sys.argv[6]) if len(sys.argv) > 6 else 2
overlap = int(sys.argv[7]) if len(sys.argv) > 7 else 1

os.environ["JAX_PLATFORMS"] = "cpu"
import jax

try:
    jax.config.update("jax_num_cpu_devices", 1)
except AttributeError:  # jax < 0.4.38: 1 CPU device is already the default
    pass
jax.config.update("jax_enable_x64", True)

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import spfft_tpu as sp
from spfft_tpu import (
    DistributedTransform,
    ExchangeType,
    ProcessingUnit,
    ScalingType,
    TransformType,
)
from spfft_tpu.parameters import distribute_triplets

sp.init_distributed(f"localhost:{port}", num_processes=nprocs, process_id=rank)
assert jax.process_count() == nprocs
mesh = sp.make_fft_mesh(nprocs)

dx, dy, dz = 8, 9, 10
rng = np.random.default_rng(42)  # same seed on both ranks -> same global plan
r2c = ttype_name == "r2c"
if r2c:
    # full half-spectrum of a real field: real output, exact value roundtrip
    real_field = rng.standard_normal((dz, dy, dx))
    full = np.fft.fftn(real_field) / (dx * dy * dz)
    xs = np.arange(dx // 2 + 1)
    triplets = np.stack(
        np.meshgrid(xs, np.arange(dy), np.arange(dz), indexing="ij"), -1
    ).reshape(-1, 3)
    values = full[triplets[:, 2], triplets[:, 1], triplets[:, 0]]
else:
    xs, ys = np.meshgrid(np.arange(dx), np.arange(dy), indexing="ij")
    keys = np.stack([xs.ravel(), ys.ravel()], axis=1)
    chosen = keys[rng.choice(len(keys), size=len(keys) // 2, replace=False)]
    triplets = np.asarray([(x, y, z) for x, y in chosen for z in range(dz)])
    values = rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
per_shard = distribute_triplets(triplets, nprocs, dy)

lut = {tuple(t): v for t, v in zip(map(tuple, triplets), values)}
values_per_shard = [np.asarray([lut[tuple(t)] for t in trip]) for trip in per_shard]

t = DistributedTransform(
    ProcessingUnit.HOST,
    TransformType.R2C if r2c else TransformType.C2C,
    dx,
    dy,
    dz,
    per_shard,
    mesh=mesh,
    exchange_type={
        "compact": ExchangeType.COMPACT_BUFFERED,
        "unbuffered": ExchangeType.UNBUFFERED,
    }.get(exchange_name, ExchangeType.BUFFERED),
    engine=engine,
    overlap=overlap,
)
ex = t._exec

# each rank supplies only its own shard's values (reference per-rank contract)
mine = set(ex._local_shard_ids())
supplied = [v if r in mine else None for r, v in enumerate(values_per_shard)]
pair = ex.pad_values(supplied)

out = ex.backward_pair(*pair)
if r2c:
    back = ex.forward_pair(out, None, ScalingType.FULL)
else:
    back = ex.forward_pair(out[0], out[1], ScalingType.FULL)

# value roundtrip on local shards
vb = ex.unpad_values(back)
for r in mine:
    err = np.abs(vb[r] - values_per_shard[r]).max()
    assert err < 1e-6, f"rank {rank} shard {r} roundtrip err {err}"

# local slab vs dense oracle
if r2c:
    oracle = real_field
else:
    dense = np.zeros((dz, dy, dx), dtype=np.complex128)
    tt = triplets
    dense[tt[:, 2] % dz, tt[:, 1] % dy, tt[:, 0] % dx] = values
    oracle = np.fft.ifftn(dense) * (dx * dy * dz)
p = ex.params
re_shards = (out if r2c else out[0]).addressable_shards
im_shards = [None] * len(re_shards) if r2c else out[1].addressable_shards
for s_re, s_im in zip(re_shards, im_shards):
    r = s_re.index[0].start
    l, o = int(p.local_z_lengths[r]), int(p.z_offsets[r])
    slab = np.asarray(s_re.data)[0, :l]
    if s_im is not None:
        slab = slab + 1j * np.asarray(s_im.data)[0, :l]
    err = np.abs(slab - oracle[o : o + l]).max()
    assert err < 1e-6, f"rank {rank} slab err {err}"

# the PUBLIC host-facing path: backward returns per-shard local slabs on a
# multi-process mesh, forward reuses the retained space buffer
slabs = t.backward(supplied)
for r in mine:
    o = int(p.z_offsets[r])
    l = int(p.local_z_lengths[r])
    err = np.abs(slabs[r] - oracle[o : o + l]).max()
    assert err < 1e-6, f"rank {rank} public slab err {err}"
assert all(slabs[r] is None for r in range(p.num_shards) if r not in mine)
vb2 = t.forward(scaling=ScalingType.FULL)
for r in mine:
    err = np.abs(vb2[r] - values_per_shard[r]).max()
    assert err < 1e-6, f"rank {rank} public roundtrip err {err}"

print(f"RANK {rank} PASS", flush=True)
