"""Performance regression gate: compare a dbench run against a baseline.

Enforces the perf trajectory instead of just recording it (``./ci.sh perf``):
every row of a ``programs/dbench.py`` scaling document (or a
``discipline_compare.py --matrix`` document — anything whose rows carry
``key``/``gflops``/``seconds_noise``) is matched by scenario key against the
committed baseline and fails the gate when its GFLOP/s fell below

    baseline_gflops * (1 - max(--tolerance, noise_current + noise_baseline))

— a **noise-aware threshold**: each row's recorded best-of-R repeat spread
(``seconds_noise``) widens the allowance, so a transiently busy host cannot
fake a regression, while a real algorithmic slide still trips. Rows present
on only one side are reported but never fail the gate (scenario matrices are
allowed to grow); ``--require-matches`` guards against gating an empty
intersection (a wrong baseline file passing vacuously).

Exit status: 0 clean, 1 usage/validation error, 3 regression (distinct, so
CI can tell "gate tripped" from "gate broken").

Usage:
    python programs/perf_gate.py current.json baseline.json
    python programs/perf_gate.py current.json baseline.json --tolerance 0.6
    python programs/perf_gate.py current.json --write-baseline baseline.json
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

DEFAULT_TOLERANCE = 0.35
# ceiling on how far recorded repeat noise may widen a row's allowance: past
# this the gate would stop being a gate (a floor at or below zero passes any
# slowdown), so pathological spreads saturate here instead
NOISE_CAP = 0.55


def load_rows(path: str) -> dict:
    """{key: row} from a dbench/matrix JSON document (validated)."""
    doc = json.loads(Path(path).read_text())
    rows = doc.get("rows", [])
    table = {}
    for i, row in enumerate(rows):
        key = row.get("key")
        if not key:
            raise ValueError(f"{path}: rows[{i}] has no scenario key")
        if "gflops" not in row:
            raise ValueError(f"{path}: rows[{i}] ({key}) has no gflops")
        table[key] = row
    return table


def gate(current: dict, baseline: dict, tolerance: float) -> tuple:
    """(regressions, improvements, unmatched) row comparisons."""
    regressions, lines, unmatched = [], [], []
    for key, row in sorted(current.items()):
        base = baseline.get(key)
        if base is None:
            unmatched.append(f"new row (no baseline): {key}")
            continue
        noise = float(row.get("seconds_noise", 0.0)) + float(
            base.get("seconds_noise", 0.0)
        )
        allowed = max(tolerance, min(noise, NOISE_CAP))
        floor = base["gflops"] * (1.0 - allowed)
        ratio = row["gflops"] / base["gflops"] if base["gflops"] else 1.0
        verdict = "REGRESSION" if row["gflops"] < floor else "ok"
        lines.append(
            f"{verdict:10s} {key}: {row['gflops']:.3f} vs {base['gflops']:.3f} "
            f"GFLOP/s (x{ratio:.2f}, floor x{1 - allowed:.2f})"
        )
        if verdict != "ok":
            regressions.append(lines[-1])
    for key in sorted(set(baseline) - set(current)):
        unmatched.append(f"baseline row not measured: {key}")
    return regressions, lines, unmatched


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("current", help="freshly measured dbench/matrix JSON")
    ap.add_argument("baseline", nargs="?", default=None,
                    help="committed baseline JSON to gate against")
    ap.add_argument("--tolerance", type=float, default=DEFAULT_TOLERANCE,
                    help="minimum allowed fractional slowdown before the row "
                    "fails (widened per-row by recorded repeat noise); CPU "
                    "meshes want a generous value")
    ap.add_argument("--require-matches", type=int, default=1,
                    help="fail unless at least this many rows matched keys "
                    "(guards against vacuously green gates)")
    ap.add_argument("--write-baseline", default=None, metavar="PATH",
                    help="copy the current document to PATH (baseline "
                    "refresh) instead of gating")
    args = ap.parse_args(argv)

    if args.write_baseline:
        doc = json.loads(Path(args.current).read_text())
        Path(args.write_baseline).write_text(json.dumps(doc, indent=2) + "\n")
        print(f"baseline written to {args.write_baseline} "
              f"({len(doc.get('rows', []))} rows)")
        return 0
    if not args.baseline:
        ap.error("baseline required unless --write-baseline is given")

    try:
        current = load_rows(args.current)
        baseline = load_rows(args.baseline)
    except (OSError, ValueError, json.JSONDecodeError) as e:
        print(f"perf_gate: {e}", file=sys.stderr)
        return 1

    regressions, lines, unmatched = gate(current, baseline, args.tolerance)
    for line in lines:
        print(line)
    for note in unmatched:
        print(f"note       {note}")
    matched = len(lines)
    if matched < args.require_matches:
        print(
            f"perf_gate: only {matched} row(s) matched the baseline "
            f"(need {args.require_matches}) — wrong baseline file?",
            file=sys.stderr,
        )
        return 1
    if regressions:
        print(
            f"perf_gate: {len(regressions)} regression(s) past the "
            f"noise-aware threshold",
            file=sys.stderr,
        )
        return 3
    print(f"perf gate clean ({matched} matched rows)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
