"""fbench: fused-vs-staged A/B benchmark through the DISPATCH path.

The shared timing discipline (``obs.perf.measure_pair_seconds``) chains the
un-jitted ``trace_*`` composition inside one ``lax.scan`` — deliberately
bypassing the IR programs — so it cannot see the thing this PR changes:
whether a host-facing pair runs as ONE compiled program per direction
(``SPFFT_TPU_FUSE=1``, the fused stage graph) or as one dispatch per stage
with materialized intermediates (``SPFFT_TPU_FUSE=0``, the staged
reference). fbench measures exactly that: staged device inputs, warmup
absorbing compilation, then best-of-R timed loops of ``pairs`` device-side
``backward_pair``/``forward_pair`` roundtrips fenced at the loop end — per-
dispatch latency and XLA's cross-stage fusion are IN the measurement, host
staging is not (the tuning-trial rule).

Output: one JSON document (schema ``spfft_tpu.ir.fbench/1``) with
gate-compatible rows (``key``/``gflops``/``seconds_noise`` —
``programs/perf_gate.py`` reads them like dbench rows), one row per fusion
variant, plus the speedup ratio and each plan's card ``ir`` section. The
committed ``BENCH_r10.json`` single-chip 256³ @15% capture and the
``./ci.sh ir`` gate both come from this harness.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

FBENCH_SCHEMA = "spfft_tpu.ir.fbench/1"


def measure_dispatch_pair(t, *, pairs: int, repeats: int, warmup: int) -> dict:
    """Best-of-``repeats`` seconds per backward+forward DISPATCH pair."""
    from spfft_tpu.sync import fence
    from spfft_tpu.tuning.runner import _stage_inputs
    from spfft_tpu.types import ScalingType

    staged = _stage_inputs(t)

    def one_pair():
        # device-side entry points: backward retains the space buffer the
        # input-less forward re-reads (both route through the IR programs)
        t.backward_pair(*staged)
        return t.forward_pair(ScalingType.FULL)

    for _ in range(max(0, warmup)):
        fence(one_pair())
    rep_seconds = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        last = None
        for _ in range(max(1, pairs)):
            last = one_pair()
        fence(last)
        rep_seconds.append((time.perf_counter() - t0) / max(1, pairs))
    best = min(rep_seconds)
    med = sorted(rep_seconds)[len(rep_seconds) // 2]
    return {
        "seconds_per_pair": best,
        "rep_seconds": rep_seconds,
        # best-vs-median spread, the gate's noise allowance input
        "seconds_noise": (med - best) / best if best > 0 else 0.0,
    }


def measure_batch_dispatch(
    t, *, batch: int, pairs: int, repeats: int, warmup: int
) -> dict:
    """Best-of-``repeats`` seconds per TRANSFORM through the batch-fused
    dispatch path: each timed iteration is ONE stacked backward+forward
    program dispatch computing ``batch`` transforms (wall / (pairs * batch)
    is the comparable per-transform unit the batched row family gates
    on)."""
    from spfft_tpu.sync import fence
    from spfft_tpu.tuning.runner import _stage_batch_inputs
    from spfft_tpu.types import ScalingType, TransformType

    re, im = _stage_batch_inputs(t, batch)
    ex = t._exec
    r2c = t.transform_type == TransformType.R2C

    def one_pair():
        out = ex.backward_pair_batch(re, im)
        assert out is not None, "batch-fused path unavailable"
        sre, sim = (out, None) if r2c else out
        pair = ex.forward_pair_batch(sre, sim, ScalingType.FULL)
        assert pair is not None, "batch-fused forward unavailable"
        return pair

    for _ in range(max(0, warmup)):
        fence(one_pair())
    rep_seconds = []
    for _ in range(max(1, repeats)):
        t0 = time.perf_counter()
        last = None
        for _ in range(max(1, pairs)):
            last = one_pair()
        fence(last)
        rep_seconds.append(
            (time.perf_counter() - t0) / (max(1, pairs) * batch)
        )
    best = min(rep_seconds)
    med = sorted(rep_seconds)[len(rep_seconds) // 2]
    return {
        "seconds_per_transform": best,
        "rep_seconds": rep_seconds,
        "seconds_noise": (med - best) / best if best > 0 else 0.0,
    }


def build(dim, sparsity, dtype, engine, fuse):
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, Transform, TransformType

    radius = float(sparsity)
    trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, radius)
    return Transform(
        ProcessingUnit.HOST
        if _platform() == "cpu"
        else ProcessingUnit.GPU,
        TransformType.C2C,
        dim,
        dim,
        dim,
        indices=trip,
        dtype=dtype,
        engine=engine,
        fuse=fuse,
    )


def _platform():
    import jax

    return jax.default_backend()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dim", type=int, default=256, help="cubic grid extent")
    ap.add_argument(
        "--radius", type=float, default=0.659,
        help="spherical cutoff radius fraction (0.659 ~ 15%% nnz)",
    )
    ap.add_argument("--dtype", default="float32", choices=["float32", "float64"])
    ap.add_argument("--engine", default="auto")
    ap.add_argument("--pairs", type=int, default=8, help="pairs per timed loop")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument(
        "--batches", type=int, nargs="*", default=[1, 4, 8],
        help="batch-fused row family: batch sizes measured through the "
        "stacked program (seconds per transform; empty disables)",
    )
    ap.add_argument("-o", "--output", default=None)
    args = ap.parse_args(argv)

    import spfft_tpu as sp

    dim = int(args.dim)
    ntot = dim**3
    flops = 2 * 5.0 * ntot * np.log2(ntot)
    rows = []
    results = {}
    for label, fuse in (("fused", True), ("staged", False)):
        t = build(dim, args.radius, np.dtype(args.dtype), args.engine, fuse)
        assert t.fused is fuse, (label, t.report()["ir"])
        m = measure_dispatch_pair(
            t, pairs=args.pairs, repeats=args.repeats, warmup=args.warmup
        )
        results[label] = m["seconds_per_pair"]
        card = t.report()
        rows.append(
            {
                "key": f"fbench:c2c:{dim}:r{args.radius}:{args.dtype}:{label}",
                "fused": fuse,
                "engine": card["engine"],
                "seconds_per_pair": m["seconds_per_pair"],
                "rep_seconds": m["rep_seconds"],
                "seconds_noise": m["seconds_noise"],
                "gflops": flops / m["seconds_per_pair"] / 1e9,
                "nnz_fraction": card["nnz_fraction"],
                "ir": card["ir"],
                "run_id": card["run_id"],
            }
        )
        print(
            f"{label:7s} {m['seconds_per_pair'] * 1e3:10.3f} ms/pair  "
            f"{rows[-1]['gflops']:9.2f} GFLOP/s  "
            f"(noise {m['seconds_noise']:.1%})",
            file=sys.stderr,
        )
    # batched row family (SPFFT_TPU_BATCH_FUSE): one fused plan, one
    # stacked program per batch size, seconds-per-transform as the
    # comparable unit — the batch=4-strictly-above-batch=1 CI gate and the
    # committed baseline's fbench batch rows come from these
    batch_results = {}
    if args.batches:
        t = build(dim, args.radius, np.dtype(args.dtype), args.engine, True)
        assert t.fused, t.report()["ir"]
        bmax = max(int(x) for x in args.batches)
        for b in sorted(set(int(x) for x in args.batches)):
            # equal WORK per timed rep across the family (pairs * bmax
            # transforms): small-batch rows otherwise time far fewer
            # transforms per rep, and their jumpier best-of would dominate
            # the batchN-vs-batch1 comparison with scheduler noise
            pairs_b = max(1, args.pairs * bmax // b)
            m = measure_batch_dispatch(
                t, batch=b, pairs=pairs_b, repeats=args.repeats,
                warmup=args.warmup,
            )
            batch_results[b] = m["seconds_per_transform"]
            card = t.report()
            # the batched row's stage attribution rides a full perf report:
            # models scale by B (attribution.batch stamps the extent), and
            # seconds are the WHOLE stacked pair, so the report's aggregate
            # gflops equals this row's per-transform figure by construction
            perf = sp.obs.perf.perf_report(
                t, m["seconds_per_transform"] * b, repeats=args.repeats,
                batch=b,
            )
            rows.append(
                {
                    "key": f"fbench:c2c:{dim}:r{args.radius}:{args.dtype}:b{b}",
                    "batch": b,
                    "engine": card["engine"],
                    "seconds_per_transform": m["seconds_per_transform"],
                    "rep_seconds": m["rep_seconds"],
                    "seconds_noise": m["seconds_noise"],
                    "gflops": flops / m["seconds_per_transform"] / 1e9,
                    "nnz_fraction": card["nnz_fraction"],
                    "ir": card["ir"],
                    "batch_provenance": card["batch"],
                    "perf": perf,
                    "run_id": card["run_id"],
                }
            )
            print(
                f"batch{b:<3d} {m['seconds_per_transform'] * 1e3:10.3f} "
                f"ms/transform  {rows[-1]['gflops']:9.2f} GFLOP/s  "
                f"(noise {m['seconds_noise']:.1%})",
                file=sys.stderr,
            )
    doc = {
        "schema": FBENCH_SCHEMA,
        "config": {
            "dim": dim,
            "radius": args.radius,
            "dtype": args.dtype,
            "engine": args.engine,
            "pairs": args.pairs,
            "repeats": args.repeats,
            "batches": sorted(batch_results),
            "platform": _platform(),
            "device_count": 1,
            "jax": __import__("jax").__version__,
            "spfft_tpu": getattr(sp, "__version__", None),
        },
        "fused_over_staged": results["staged"] / results["fused"],
        "rows": rows,
    }
    if 1 in batch_results and any(b > 1 for b in batch_results):
        bmax = max(b for b in batch_results if b > 1)
        doc["batch_over_single"] = batch_results[1] / batch_results[bmax]
    out = json.dumps(doc, indent=1)
    if args.output:
        Path(args.output).write_text(out)
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(out)
    print(
        f"fused-over-staged speedup: x{doc['fused_over_staged']:.3f}",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
