"""Round-4 on-chip measurement batch — ONE process, one device claim.

Chip-gated A/Bs for this round's engine work, batched so a flaky tunnel is
claimed once (the round-3 discipline, programs/round3_measurements.py):

1. blocked sparse-y at the 256^3/15% spherical headline (auto G=4 vs off vs
   G=2/G=8) — the y-stage flop cut above the per-slot crossover,
2. phase-table operands vs the round-3 embedded/in-trace forms at 256^3 and
   512^3 (the 512^3 regression suspect: per-apply in-trace cos/sin),
3. 512^3 C2C sph15 local with the round-4 defaults (driver config-5 size),
4. f64 512^3 R2C host-facing pair with chunked staging (VERDICT r3 item 8;
   round-3 row: ~174 s/pair unchunked),
5. distributed multi-transform: 4 P=1-mesh transforms fused into one jitted
   chain vs 1 (the `-m 4 --shards 1` row, VERDICT r3 item 7).

Results append incrementally to ``bench_results/round4_onchip.json`` so a
mid-batch death keeps earlier rows. One variable per arm; every arm pins the
env knobs it depends on.

Usage: python programs/round4_measurements.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round4_onchip.json"
)


def flops_pair(dim):
    import numpy as np

    n = dim**3
    return 2 * 5.0 * n * np.log2(n)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="short chains (smoke)")
    ap.add_argument(
        "--skip-f64", action="store_true", help="skip the slow f64 staging arm"
    )
    args = ap.parse_args()

    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round4_measurements", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev} ({dev.client.platform_version})", file=sys.stderr)
    disarm()

    import os

    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )
    from spfft_tpu.parameters import distribute_triplets

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def time_chain(ex, re0, im0, chain):
        phase = getattr(ex, "phase_operands", ())

        # phase operands thread through the jit argument list (never closure
        # constants — ops/lanecopy.phase_rep_operands)
        def chain_fn(r, i, ph):
            def body(carry, _):
                sre, sim = ex.trace_backward(*carry, phase=ph)
                return (
                    ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph),
                    None,
                )

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, wim = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, cim = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    def with_env(envs, fn):
        saved = {k: os.environ.get(k) for k in envs}
        for k, v in envs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            return fn()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def measure_local(name, dim, sparsity, chain, env=None):
        def run():
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
            t = Transform(
                ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
                indices=trip, dtype=np.float32, engine="mxu",
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            n = len(trip)
            re0 = ex.put(rng.standard_normal(n).astype(np.float32))
            im0 = ex.put(rng.standard_normal(n).astype(np.float32))
            best, err = time_chain(ex, re0, im0, chain)
            record({
                "name": name, "dim": dim, "chain": chain,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
                "blocked_buckets": (
                    len(getattr(ex, "_sparse_y_blocked", None) or ())
                ),
                "phase_operands": len(getattr(ex, "phase_operands", ())),
            })

        try:
            with_env(env or {}, run)
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})

    CH = 48 if args.quick else 384
    CH512 = 8 if args.quick else 48

    # ---- 1+2: headline blocked sparse-y + operand arms at 256^3 ----
    # every arm pins the three knobs it varies (one variable per arm);
    # SPFFT_TPU_SPARSE_Y stays unset (auto; it never engages at 0.659)
    base = {"SPFFT_TPU_SPARSE_Y": None}
    measure_local("c2c_256_s15_r4_default", 256, 0.659, CH, env={**base})
    measure_local(
        "c2c_256_s15_blocked_off", 256, 0.659, CH,
        env={**base, "SPFFT_TPU_SPARSE_Y_BLOCKS": "0"},
    )
    measure_local(
        "c2c_256_s15_blocked_g2", 256, 0.659, CH,
        env={**base, "SPFFT_TPU_SPARSE_Y_BLOCKS": "2"},
    )
    measure_local(
        "c2c_256_s15_blocked_g8", 256, 0.659, CH,
        env={**base, "SPFFT_TPU_SPARSE_Y_BLOCKS": "8"},
    )
    # operands OFF, blocked OFF == the round-3 shipped engine (6.15 ms row)
    measure_local(
        "c2c_256_s15_r3_config", 256, 0.659, CH,
        env={
            **base,
            "SPFFT_TPU_SPARSE_Y_BLOCKS": "0",
            "SPFFT_TPU_PHASE_DEVICE_MB": "0",
        },
    )
    # 128^3 headline-class re-pin under the new defaults
    measure_local("c2c_128_sph15_r4", 128, 0.659, 96 if args.quick else 768)

    # ---- 3: 512^3 local (driver config-5 size class) ----
    measure_local("c2c_512_sph15_r4_default", 512, 0.659, CH512, env={**base})
    measure_local(
        "c2c_512_sph15_blocked_off", 512, 0.659, CH512,
        env={**base, "SPFFT_TPU_SPARSE_Y_BLOCKS": "0"},
    )
    # operands off -> the round-3 in-trace phase rep (the 87 ms / 416 GFLOP/s
    # row): isolates how much of the 512^3 regression was phase regeneration
    measure_local(
        "c2c_512_sph15_r3_config", 512, 0.659, CH512,
        env={
            **base,
            "SPFFT_TPU_SPARSE_Y_BLOCKS": "0",
            "SPFFT_TPU_PHASE_DEVICE_MB": "0",
        },
    )

    # ---- 4: f64 512^3 R2C host-facing pair, chunked staging ----
    if not args.skip_f64:
        def run_f64():
            jax.config.update("jax_enable_x64", True)
            try:
                dim = 128 if args.quick else 512
                trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
                # hermitian non-redundant half (x >= 0 of the centered sphere)
                trip = trip[trip[:, 0] >= 0]
                t = Transform(
                    ProcessingUnit.GPU, TransformType.R2C, dim, dim, dim,
                    indices=trip, dtype=np.float64,
                )
                rng = np.random.default_rng(0)
                v = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(
                    len(trip)
                )
                # one warm host-facing pair (compile), then two timed
                t.backward(v)
                t.forward(scaling=ScalingType.FULL)
                best = float("inf")
                for _ in range(2):
                    t0 = time.perf_counter()
                    space = t.backward(v)
                    out = t.forward(space, scaling=ScalingType.FULL)
                    best = min(best, time.perf_counter() - t0)
                err = float(np.abs(out - v).max() / np.abs(v).max())
                record({
                    "name": "f64_512_r2c_hostfacing_chunked",
                    "dim": dim,
                    "s_per_pair": round(best, 1),
                    "roundtrip_rel_err": err,
                    "stage_chunk_mb": os.environ.get(
                        "SPFFT_TPU_STAGE_CHUNK_MB", "256(default)"
                    ),
                })
            finally:
                jax.config.update("jax_enable_x64", False)

        try:
            run_f64()
        except Exception as e:
            record({"name": "f64_512_r2c_hostfacing_chunked",
                    "error": f"{type(e).__name__}: {e}"})

    # ---- 5: distributed multi-transform (-m 4 --shards 1) ----
    def measure_dist_multi(name, m, dim, sparsity, chain):
        try:
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
            per = distribute_triplets(trip, 1, dim)
            mesh = sp.make_fft_mesh(1)
            ts = [
                DistributedTransform(
                    ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
                    per, mesh=mesh, dtype=np.float32, engine="mxu",
                )
                for _ in range(m)
            ]
            exs = [t._exec for t in ts]
            rng = np.random.default_rng(0)
            vals = [
                (rng.standard_normal(len(p)) + 1j * rng.standard_normal(len(p)))
                .astype(np.complex64)
                for p in per
            ]
            pairs = [ex.pad_values(vals) for ex in exs]

            def body(carry, _):
                outs = []
                for ex, (re, im) in zip(exs, carry):
                    s = ex.trace_backward(re, im)
                    outs.append(ex.trace_forward(*s, ScalingType.FULL))
                return tuple(outs), None

            step = jax.jit(
                lambda ps: jax.lax.scan(body, ps, None, length=chain)[0]
            )
            out = step(tuple(pairs))
            float(jax.device_get(out[0][0].ravel()[0]))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = step(tuple(pairs))
                float(jax.device_get(out[0][0].ravel()[0]))
                best = min(best, (time.perf_counter() - t0) / (chain * m))
            record({
                "name": name, "m": m, "dim": dim, "chain": chain,
                "ms_per_transform_pair": round(best * 1e3, 3),
                "gflops_per_transform": round(flops_pair(dim) / best / 1e9, 1),
            })
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})

    CHM = 12 if args.quick else 96
    measure_dist_multi("dist1_m1_128_sph15", 1, 128, 0.659, CHM)
    measure_dist_multi("dist1_m4_128_sph15", 4, 128, 0.659, CHM)

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
