"""Run a traced roundtrip and export the flight recorder.

The execution-trace CLI (spfft_tpu.obs.trace): arms the flight recorder,
builds a plan, runs one backward+forward roundtrip, and exports what the
recorder saw — the event table on stdout (filterable with ``--last`` /
``--run``), the schema-pinned snapshot JSON (``-o``), and Chrome trace-event
format (``--chrome``) loadable in Perfetto / chrome://tracing, one track per
host phase. The snapshot is validated (trace.validate_trace) before it is
written; a malformed event exits nonzero, so ci.sh catches trace-schema
drift without TPU hardware.

Usage:
    python programs/trace.py -d 32 32 32 --chrome trace.json
    python programs/trace.py -d 16 16 16 --shards 2 --last 20
    python programs/trace.py -d 16 16 16 --run r000001 -o snapshot.json
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def build_plan(args):
    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, TransformType

    dx, dy, dz = args.d
    radius = sp.spherical_radius_for_fraction(args.s)
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, min(radius, 1.0))
    if args.shards > 1:
        from spfft_tpu.parallel import make_fft_mesh

        mesh = make_fft_mesh(args.shards)
        return sp.DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, trip,
            mesh=mesh, engine=args.engine,
        )
    return sp.Transform(
        ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, indices=trip,
        engine=args.engine,
    )


def format_event(ev: dict) -> str:
    args = dict(ev["args"])
    label = args.pop("label", None)
    name = f"{ev['name']}:{label}" if label else ev["name"]
    rest = " ".join(f"{k}={v}" for k, v in args.items())
    return (
        f"{ev['seq']:>6d} {ev['ts'] * 1e3:>10.3f}ms {ev['run'] or '-':>8} "
        f"{ev['ph']} {name:<24} {rest}"
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-d", nargs=3, type=int, default=[16, 16, 16],
                    metavar=("X", "Y", "Z"))
    ap.add_argument("-s", type=float, default=0.15, help="nonzero fraction")
    ap.add_argument("--engine", default="auto", choices=["auto", "xla", "mxu"])
    ap.add_argument("--shards", type=int, default=1,
                    help="1-D slab mesh width (1 = local plan)")
    ap.add_argument("--last", type=int, default=None, metavar="N",
                    help="print only the last N events")
    ap.add_argument("--run", default=None, metavar="ID",
                    help="print only events of run ID (e.g. r000001)")
    ap.add_argument("--chrome", default=None, metavar="PATH",
                    help="write Chrome trace-event JSON here")
    ap.add_argument("-o", default=None, help="write the snapshot JSON here")
    args = ap.parse_args(argv)

    # mesh-width CPU devices must exist before the first backend touch
    if args.shards > 1:
        from spfft_tpu.parallel.mesh import ensure_virtual_devices

        ensure_virtual_devices(args.shards, warn=True, platform="cpu")

    from spfft_tpu import ScalingType
    from spfft_tpu.obs import trace

    trace.enable()  # the CLI's whole point — arm regardless of SPFFT_TPU_TRACE

    plan = build_plan(args)
    rng = np.random.default_rng(0)
    if args.shards > 1:
        values = [
            rng.standard_normal(plan.num_local_elements(r))
            + 1j * rng.standard_normal(plan.num_local_elements(r))
            for r in range(plan.num_shards)
        ]
    else:
        n = plan.num_local_elements
        values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
    plan.backward(values)
    plan.forward(scaling=ScalingType.FULL)

    snap = trace.snapshot()
    missing = trace.validate_trace(snap)

    shown = snap["events"]
    if args.run:
        shown = [ev for ev in shown if ev["run"] == args.run]
    if args.last is not None:
        shown = shown[-args.last:]
    print(
        f"run {plan.report()['run_id']}: {len(snap['events'])} events "
        f"recorded ({snap['dropped']} dropped, capacity {snap['capacity']}), "
        f"{len(shown)} shown"
    )
    for ev in shown:
        print(format_event(ev))

    if args.o:
        Path(args.o).write_text(json.dumps(snap, indent=1) + "\n")
        print(f"snapshot written to {args.o}")
    if args.chrome:
        Path(args.chrome).write_text(
            json.dumps(trace.chrome_trace(snap)) + "\n"
        )
        print(f"chrome trace written to {args.chrome} "
              "(open in Perfetto / chrome://tracing)")
    if missing:
        print(f"trace schema INCOMPLETE, missing: {missing}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
