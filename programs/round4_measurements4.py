"""Round-4 on-chip batch 4 (final): pencil engine on the chip + R2C re-pin.

- The 2-D pencil MXU engine has only ever run on the virtual CPU mesh; a
  1x1 pencil mesh on the chip proves the pipeline (two exchanges, slot
  permutation, x-matrix folding) compiles and performs on real hardware.
- R2C 128^3 dense re-pin under the round-4 engine (dense-promoted copy
  plans touch R2C paths too).

Appends to bench_results/round4_onchip4.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round4_onchip4.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round4_measurements4", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def flops_pair(dim):
        n = dim**3
        return 2 * 5.0 * n * np.log2(n)

    def chain_time(ex, re0, im0, chain, r2c=False):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                if r2c:
                    space = ex.trace_backward(carry[0], carry[1], phase=ph)
                    out = ex.trace_forward(space, None, ScalingType.FULL, phase=ph)
                else:
                    sre, sim = ex.trace_backward(*carry, phase=ph)
                    out = ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph)
                return out, None

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, wim = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, _ = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    # ---- R2C 128^3 dense re-pin ----
    try:
        dim = 128
        xs, ys, zs = np.meshgrid(
            np.arange(dim // 2 + 1), np.arange(dim), np.arange(dim),
            indexing="ij",
        )
        # hermitian non-redundant dense set (reference benchmark model)
        keep = ~((xs == 0) & (ys > dim // 2))
        trip = np.stack(
            [xs[keep].ravel(), ys[keep].ravel(), zs[keep].ravel()], 1
        ).astype(np.int32)
        t = Transform(
            ProcessingUnit.GPU, TransformType.R2C, dim, dim, dim,
            indices=trip, dtype=np.float32, engine="mxu",
        )
        ex = t._exec
        rng = np.random.default_rng(0)
        n = len(trip)
        re0 = ex.put(rng.standard_normal(n).astype(np.float32))
        im0 = ex.put(rng.standard_normal(n).astype(np.float32))
        best, _ = chain_time(ex, re0, im0, 512, r2c=True)
        record({
            "name": "r2c_128_dense_r4",
            "ms_per_pair": round(best * 1e3, 3),
            "gflops": round(flops_pair(dim) / best / 1e9, 1),
        })
    except Exception as e:
        record({"name": "r2c_128_dense_r4", "error": f"{type(e).__name__}: {e}"})

    # ---- pencil 1x1 on chip, 256^3 C2C 15% spherical ----
    try:
        dim = 256
        trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
        mesh = sp.make_fft_mesh2(1, 1)
        t = DistributedTransform(
            ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, trip,
            mesh=mesh, dtype=np.float32, engine="mxu",
        )
        ex = t._exec
        rng = np.random.default_rng(0)
        pairs = ex.pad_values([
            (rng.standard_normal(t.num_local_elements(0))
             + 1j * rng.standard_normal(t.num_local_elements(0))).astype(np.complex64)
        ])
        best, err = chain_time(ex, pairs[0], pairs[1], 96)
        record({
            "name": "pencil1x1_c2c_256_sph15_onchip",
            "ms_per_pair": round(best * 1e3, 3),
            "gflops": round(flops_pair(dim) / best / 1e9, 1),
            "roundtrip_err": err,
            "engine": t._engine,
        })
    except Exception as e:
        record({"name": "pencil1x1_c2c_256_sph15_onchip",
                "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
