"""Pallas-DMA row-copy A/B, attempt 2 (fixed SMEM plumbing).

Attempt 1 (round5_pallas_dma.json): the (R,) scalar-prefetch index array is
1.4 MB > the 1 MB SMEM, so every Pallas arm failed at compile. This version
feeds each program its own (T,) index slice through a blocked SMEM in_spec
instead (no scalar prefetch), which bounds SMEM at T*4 bytes. The ring
variant is dropped (it genuinely needs all R indices resident).

Context bar from attempt 1: xla_take 5.411 ms (15.0 ns/row), contiguous
dense copy of the same bytes 3.078 ms (8.5 ns/row) — the gather is already
within 1.76x of the copy floor, so the best possible Pallas win is ~2.3 ms
per apply at 512^3 geometry.

Appends to bench_results/round5_pallas_dma.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_pallas_dma.json"
)

LANE = 128


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "microbench_pallas_dma2", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    rng = np.random.default_rng(0)
    M = 735_000
    R = 360_448
    idx = np.sort(rng.choice(M, size=R, replace=False)).astype(np.int32)
    src = jnp.asarray(rng.standard_normal((M, LANE)).astype(np.float32))
    idx_t = jnp.asarray(idx)

    REPS = 32

    def timed(name, fn, extra=None):
        @jax.jit
        def loop(s):
            def body(carry, _):
                out = fn(carry)
                return carry.at[:LANE, :].set(out[:LANE, :]), ()

            final, _ = jax.lax.scan(body, s, None, length=REPS)
            return final.ravel()[0]

        try:
            float(jax.device_get(loop(src)))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = loop(src)
                float(jax.device_get(out))
                best = min(best, (time.perf_counter() - t0) / REPS)
            row = {"name": name, "ms": round(best * 1e3, 3),
                   "ns_per_row": round(best / R * 1e9, 2)}
            if extra:
                row.update(extra)
            record(row)
            return best
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"[:400]})
            return None

    def make_grid_kernel(T):
        def kernel(idx_ref, src_ref, out_ref, sems):
            for j in range(T):
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[j]], out_ref.at[j], sems.at[j]
                ).start()
            for j in range(T):
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[j]], out_ref.at[j], sems.at[j]
                ).wait()

        call = pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, LANE), jnp.float32),
            grid=(R // T,),
            in_specs=[
                pl.BlockSpec((T,), lambda i: (i,), memory_space=pltpu.SMEM),
                pl.BlockSpec(memory_space=pltpu.ANY),
            ],
            out_specs=pl.BlockSpec(
                (T, LANE), lambda i: (i, 0), memory_space=pltpu.VMEM
            ),
            scratch_shapes=[pltpu.SemaphoreType.DMA((T,))],
        )
        return lambda s: call(idx_t, s)

    for T in (16, 64, 256, 1024):
        k = make_grid_kernel(T)
        timed(f"pallas_grid2_T{T}", k, extra={"T": T})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
