"""Per-stage ablation of the CURRENT MXU backward pipeline (blocked sparse-y
+ operand-threaded tables), at any size including 512^3 — plan operands ride
the jit argument list, so the 512^3-class constants that broke
microbench_ablate's closures (HTTP 413) never enter the program body.

Methodology: DEPENDENT chains inside one jitted lax.scan (see
microbench_ablate.py), scalar-fetch fence, stage prefixes of the backward
pipeline so successive rows isolate stage costs by subtraction.

Usage: python programs/ablate_blocked.py [--dim 512] [--reps 8]
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax

import spfft_tpu as sp
from spfft_tpu.execution_mxu import MxuLocalExecution
from spfft_tpu.ops import fft as offt
from spfft_tpu.ops import lanecopy
from spfft_tpu.parameters import make_local_parameters
from spfft_tpu.types import TransformType


def timeit_chain(fn, x0, ops, reps):
    @jax.jit
    def loop(a, b, ph):
        def body(carry, _):
            return fn(*carry, ph), ()

        (r, i), _ = jax.lax.scan(body, (a, b), None, length=reps)
        return r.ravel()[0] + i.ravel()[0]

    float(loop(*x0, ops))
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        float(loop(*x0, ops))
        best = min(best, (time.perf_counter() - t0) / reps)
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=512)
    ap.add_argument("--reps", type=int, default=8)
    args = ap.parse_args()
    d = args.dim
    trip = sp.create_spherical_cutoff_triplets(d, d, d, 0.659)
    params = make_local_parameters(TransformType.C2C, d, d, d, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float32)
    p = params
    S, Z, Y, A = p.num_sticks, p.dim_z, p.dim_y, ex._num_x_active
    N = p.num_values
    blocked = ex._sparse_y_blocked
    print(
        f"plan: S={S} Z={Z} Y={Y} A={A} values={N} "
        f"buckets={len(blocked) if blocked else 0} "
        f"operands={len(ex.phase_operands)}",
        flush=True,
    )
    prec = ex._precision
    rt = ex.real_dtype
    rng = np.random.default_rng(0)
    vpair = tuple(
        ex.put(rng.standard_normal(N).astype(np.float32)) for _ in range(2)
    )
    ops = ex.phase_operands

    def phase_undo(sre, sim, ph):
        if ex._phase is None:
            return sre, sim
        phase_ops, _ = ex._split_operands(ph)
        cos_t, sin_t = ex._phase_tables(phase_ops)
        return lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, -1)

    def blocked_y(sre, sim, ph):
        _, mat_ops = ex._split_operands(ph)
        return ex._blocked_y_backward(sre, sim, mat_ops)

    def s_decompress(a, b, ph):
        sre, sim = ex._decompress(a, b)
        return sre.ravel()[:N], sim.ravel()[:N]

    def s_decompress_z(a, b, ph):
        sre, sim = ex._decompress(a, b)
        sre, sim = offt.complex_matmul(sre, sim, *ex._wz_b, "sz,zk->sk", prec)
        sre, sim = phase_undo(sre, sim, ph)
        return sre.ravel()[:N], sim.ravel()[:N]

    def s_through_y(a, b, ph):
        sre, sim = ex._decompress(a, b)
        sre, sim = offt.complex_matmul(sre, sim, *ex._wz_b, "sz,zk->sk", prec)
        sre, sim = phase_undo(sre, sim, ph)
        gre, gim = blocked_y(sre, sim, ph)
        return gre.ravel()[:N], gim.ravel()[:N]

    def s_full(a, b, ph):
        gre, gim = ex._backward_impl(a, b, *ph)
        return gre.ravel()[:N], gim.ravel()[:N]

    rows = [
        ("decompress", s_decompress),
        ("decompress+z(+phase)", s_decompress_z),
        ("... +blocked-y", s_through_y),
        ("FULL backward", s_full),
    ]
    if blocked is None:
        rows = [r for r in rows if "blocked" not in r[0]]
    for name, fn in rows:
        t = timeit_chain(fn, vpair, ops, args.reps)
        print(f"{name:24s} {t*1e3:9.3f} ms", flush=True)


if __name__ == "__main__":
    main()
