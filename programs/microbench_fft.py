"""Microbenchmark: batched 1D complex DFT strategies on TPU.

Compares, for the batched stage shapes the 3D pipeline actually issues
((batch, N) contracted over N):

  direct   -- (batch, N) @ (N, N) DFT matrix, 4 real matmuls (current MXU engine)
  ct       -- Cooley-Tukey four-step N = N1*N2: DFT over N2, twiddle, DFT over N1
  xla_fft  -- jnp.fft.fft along the last axis (XLA's native FFT lowering)

Run: python programs/microbench_fft.py [--ns 128,256,512] [--reps 20]

!! TIMING METHODOLOGY SUPERSEDED: this harness times independent repeats with
jax.block_until_ready, which neither prevents XLA from hoisting loop-invariant
work nor fences execution on the tunneled axon TPU. Numbers from it are
unreliable; use the dependent-chain + scalar-fetch methodology of
programs/microbench_ablate.py / microbench_pallas.py instead. Kept for the
record of which variants were explored. (The direct-matmul-DFT design choice it
informed was re-validated with correct timing: see BASELINE.md "Four-step
factored DFT".)
"""
from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp

PRECISION = jax.lax.Precision.HIGHEST


def dft_matrix(n, sign=+1, dtype=np.float32):
    k = np.arange(n)
    w = np.exp(sign * 2j * np.pi * np.outer(k, k) / n)
    return w.real.astype(dtype), w.imag.astype(dtype)


def cmatmul(xr, xi, wr, wi, spec):
    yr = jnp.einsum(spec, xr, wr, precision=PRECISION) - jnp.einsum(
        spec, xi, wi, precision=PRECISION
    )
    yi = jnp.einsum(spec, xr, wi, precision=PRECISION) + jnp.einsum(
        spec, xi, wr, precision=PRECISION
    )
    return yr, yi


def make_direct(n, dtype):
    wr, wi = dft_matrix(n, dtype=dtype)
    wr, wi = jnp.asarray(wr), jnp.asarray(wi)

    def f(xr, xi):
        return cmatmul(xr, xi, wr, wi, "bn,nk->bk")

    return jax.jit(f)


def split_factors(n):
    """Pick N1*N2 = n with N2 as close to 128 as possible (MXU contraction dim)."""
    best = None
    for n2 in range(1, n + 1):
        if n % n2:
            continue
        n1 = n // n2
        score = abs(n2 - 128) + abs(n1 - 128) * 0.1
        if best is None or score < best[0]:
            best = (score, n1, n2)
    return best[1], best[2]


def make_ct(n, dtype):
    n1, n2 = split_factors(n)
    w2r, w2i = dft_matrix(n2, dtype=dtype)
    w1r, w1i = dft_matrix(n1, dtype=dtype)
    # twiddle[j1, k2] = exp(2i pi j1 k2 / n)  (sign +1 backward convention)
    j1, k2 = np.arange(n1), np.arange(n2)
    tw = np.exp(2j * np.pi * np.outer(j1, k2) / n)
    twr, twi = jnp.asarray(tw.real.astype(dtype)), jnp.asarray(tw.imag.astype(dtype))
    w2r, w2i, w1r, w1i = map(jnp.asarray, (w2r, w2i, w1r, w1i))

    def f(xr, xi):
        # x[b, j1*n2 + j2] -> X[b, k1 + n1*k2]  (four-step)
        xr_ = xr.reshape(-1, n1, n2)
        xi_ = xi.reshape(-1, n1, n2)
        # inner DFT over j2 -> k2
        yr, yi = cmatmul(xr_, xi_, w2r, w2i, "bjn,nk->bjk")
        # twiddle
        zr = yr * twr - yi * twi
        zi = yr * twi + yi * twr
        # outer DFT over j1 -> k1
        or_, oi_ = cmatmul(zr, zi, w1r, w1i, "bjk,jm->bmk")
        # output index is k1 + n1*k2 => layout (m, k) flatten order (k2 major?):
        # X[k1 + n1*k2] -> reshape (n2, n1) transposed; return flattened (b, n)
        return or_.transpose(0, 2, 1).reshape(-1, n), oi_.transpose(0, 2, 1).reshape(-1, n)

    return jax.jit(f), (n1, n2)


def make_xla_fft(n):
    def f(xr, xi):
        out = jnp.fft.ifft(jax.lax.complex(xr, xi), axis=-1) * n
        return out.real, out.imag

    return jax.jit(f)


def timeit(f, args, reps):
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ns", default="128,256,512")
    ap.add_argument("--reps", type=int, default=20)
    ap.add_argument("--dtype", default="float32")
    args = ap.parse_args()
    dtype = np.dtype(args.dtype)

    rng = np.random.default_rng(0)
    for n in [int(x) for x in args.ns.split(",")]:
        batch = n * n
        xr = jnp.asarray(rng.standard_normal((batch, n)).astype(dtype))
        xi = jnp.asarray(rng.standard_normal((batch, n)).astype(dtype))

        direct = make_direct(n, dtype)
        ct, (n1, n2) = make_ct(n, dtype)
        xf = make_xla_fft(n)

        # correctness vs numpy
        ref = np.fft.ifft(np.asarray(xr) + 1j * np.asarray(xi), axis=-1) * n
        for name, f in (("direct", direct), ("ct", ct), ("xla_fft", xf)):
            rr, ri = f(xr, xi)
            err = np.max(np.abs((np.asarray(rr) + 1j * np.asarray(ri)) - ref)) / np.max(
                np.abs(ref)
            )
            t = timeit(f, (xr, xi), args.reps)
            extra = f" (n1={n1},n2={n2})" if name == "ct" else ""
            gflops = 5 * batch * n * np.log2(n) / t / 1e9
            print(
                f"N={n:4d} batch={batch:6d} {name:8s}{extra:16s} "
                f"{t*1e3:8.3f} ms  rel_err={err:.2e}  eff_gflops={gflops:8.1f}"
            )


if __name__ == "__main__":
    main()
