"""Ablate the FULL backward pipeline to find its bottleneck stage.

Timing methodology: DEPENDENT chains inside one compiled lax.scan — each
iteration's input derives from the previous iteration's output (sliced/padded
back to the input shape), so XLA cannot hoist the body out of the loop, and a
scalar fetch fences completion (block_until_ready does not wait on the axon
tunnel). Loop-invariant bodies get hoisted entirely (measured: a 5.8 ms
pipeline "runs" in 1.3 ms with independent repeats).
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import spfft_tpu as sp
from spfft_tpu.execution_mxu import MxuLocalExecution
from spfft_tpu.ops import fft as offt
from spfft_tpu.parameters import make_local_parameters
from spfft_tpu.types import TransformType


def timeit_chain(fn, x0, reps=60):
    """fn maps a pair (re, im) -> pair of the SAME shapes (caller adapts)."""

    @jax.jit
    def loop(a, b):
        def body(carry, _):
            return fn(*carry), ()

        (r, i), _ = jax.lax.scan(body, (a, b), None, length=reps)
        return r.ravel()[0] + i.ravel()[0]

    float(loop(*x0))
    t0 = time.perf_counter()
    float(loop(*x0))
    return (time.perf_counter() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--sparsity", type=float, default=0.15)
    ap.add_argument("--reps", type=int, default=60)
    args = ap.parse_args()
    d = args.dim
    radius = float((6.0 * args.sparsity / np.pi) ** (1.0 / 3.0))
    trip = sp.create_spherical_cutoff_triplets(d, d, d, radius)
    params = make_local_parameters(TransformType.C2C, d, d, d, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float32)
    p = params
    S, Z, Y, A = p.num_sticks, p.dim_z, p.dim_y, ex._num_x_active
    N = p.num_values
    print(f"plan: S={S} Z={Z} Y={Y} A={A} values={N}")
    prec = ex._precision
    rng = np.random.default_rng(0)
    vpair = tuple(
        jnp.asarray(rng.standard_normal(N).astype(np.float32)) for _ in range(2)
    )
    spair = tuple(
        jnp.asarray(rng.standard_normal((S, Z)).astype(np.float32)) for _ in range(2)
    )

    # Every fn below maps value-pair -> value-pair or stick-pair -> stick-pair
    # so chains stay dependent. Grid outputs are folded back by slicing.

    def full(a, b):
        gr, gi = ex._backward_impl(a, b)
        return gr.ravel()[:N], gi.ravel()[:N]

    def no_decompress(a, b):
        s2 = offt.complex_matmul(a, b, *ex._wz_b, "sz,zk->sk", prec)
        g = ex._expand(*s2)
        g = offt.complex_matmul(*g, *ex._wy_b, "yxz,yk->kxz", prec)
        g = offt.complex_matmul(*g, *ex._wx_b, "kxz,xl->klz", prec)
        return g[0].reshape(-1)[: S * Z].reshape(S, Z), g[1].reshape(-1)[: S * Z].reshape(S, Z)

    def matmuls_only(a, b):
        s2 = offt.complex_matmul(a, b, *ex._wz_b, "sz,zk->sk", prec)
        g = (
            jnp.broadcast_to(s2[0][: 1, :], (Y * A, Z)).reshape(Y, A, Z),
            jnp.broadcast_to(s2[1][: 1, :], (Y * A, Z)).reshape(Y, A, Z),
        )
        g = offt.complex_matmul(*g, *ex._wy_b, "yxz,yk->kxz", prec)
        g = offt.complex_matmul(*g, *ex._wx_b, "kxz,xl->klz", prec)
        return g[0].reshape(-1)[: S * Z].reshape(S, Z), g[1].reshape(-1)[: S * Z].reshape(S, Z)

    def decompress_z(a, b):
        s2 = ex._decompress(a, b)
        s2 = offt.complex_matmul(*s2, *ex._wz_b, "sz,zk->sk", prec)
        return s2[0].ravel()[:N], s2[1].ravel()[:N]

    def decompress_z_expand(a, b):
        s2 = ex._decompress(a, b)
        s2 = offt.complex_matmul(*s2, *ex._wz_b, "sz,zk->sk", prec)
        g = ex._expand(*s2)
        return g[0].reshape(-1)[:N], g[1].reshape(-1)[:N]

    def z_only(a, b):
        return offt.complex_matmul(a, b, *ex._wz_b, "sz,zk->sk", prec)

    rows = [
        ("FULL backward", full, vpair),
        ("- decompress", no_decompress, spair),
        ("matmuls only (no gathers)", matmuls_only, spair),
        ("decompress+z", decompress_z, vpair),
        ("decompress+z+expand", decompress_z_expand, vpair),
        ("z matmul only", z_only, spair),
    ]
    for name, fn, x0 in rows:
        t = timeit_chain(fn, x0, reps=args.reps)
        print(f"{name:26s} {t*1e3:8.3f} ms", flush=True)


if __name__ == "__main__":
    main()
