"""Round-3 on-chip measurement batch — ONE process, one device claim.

Runs every chip-gated A/B and re-measurement in a single interpreter so a
flaky tunnel is claimed once: the sparse-y arm (ROADMAP P1), the
lane-rotation arm (sanity re-check), the 32^3 long-chain re-measure, the
exchange-specialized P=1 distributed plan, the 512^3 R2C config-5 shape, and
the ragged-all-to-all backend probe. Results append incrementally to
``bench_results/round3_onchip.json`` so a mid-batch death keeps earlier rows.

Timing protocol: CHAIN dependent roundtrips inside one jitted ``lax.scan``
with a scalar host fetch (the tunnel's ~110 ms fixed per-call cost amortized
to noise; see bench.py / BASELINE.md).

Usage: python programs/round3_measurements.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

# NEVER bench_results/round3_onchip.json — that file is the archived
# 2026-07-31 capture cited by BASELINE.md/ROADMAP.md; re-runs (including
# --quick smoke runs off-chip) must not clobber it.
OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round3_onchip_rerun.json"
)


def flops_pair(dim):
    import numpy as np

    n = dim**3
    return 2 * 5.0 * n * np.log2(n)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="short chains (smoke)")
    args = ap.parse_args()

    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round3_measurements", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev} ({dev.client.platform_version})", file=sys.stderr)
    disarm()

    import os

    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ExchangeType,
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )
    from spfft_tpu.ops import lanecopy
    from spfft_tpu.parameters import distribute_triplets

    results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def time_chain(trace_backward, trace_forward, re0, im0, chain):
        def body(carry, _):
            sre, sim = trace_backward(*carry)
            return trace_forward(sre, sim, ScalingType.FULL), None

        step = jax.jit(lambda r, i: jax.lax.scan(body, (r, i), None, length=chain)[0])
        wre, wim = step(re0, im0)
        np.asarray(jax.device_get(wre.ravel()[0]))  # compile + warm
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, cim = step(re0, im0)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max())
        return best, err

    def measure_local(name, dim, sparsity, chain, env=None, no_rotation=False,
                      precision="highest"):
        envs = dict(env or {})
        saved = {k: os.environ.get(k) for k in envs}
        os.environ.update(envs)
        orig_rot = lanecopy.plan_alignment_rotations
        if no_rotation:
            lanecopy.plan_alignment_rotations = lambda *a, **k: None
        try:
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
            t = Transform(
                ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
                indices=trip, dtype=np.float32, precision=precision,
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            n = len(trip)
            re0 = ex.put(rng.standard_normal(n).astype(np.float32))
            im0 = ex.put(rng.standard_normal(n).astype(np.float32))
            best, err = time_chain(ex.trace_backward, ex.trace_forward, re0, im0, chain)
            row = {
                "name": name, "dim": dim, "chain": chain,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
                "sparse_y_engaged": bool(getattr(ex, "_sparse_y", False)),
                "rotations": not no_rotation and ex._phase is not None,
            }
            record(row)
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})
        finally:
            lanecopy.plan_alignment_rotations = orig_rot
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def measure_dist1(name, dim, sparsity, chain, env=None):
        envs = dict(env or {})
        saved = {k: os.environ.get(k) for k in envs}
        os.environ.update(envs)
        try:
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
            per = distribute_triplets(trip, 1, dim)
            mesh = sp.make_fft_mesh(1)
            t = DistributedTransform(
                ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, per,
                mesh=mesh, dtype=np.float32, engine="mxu",
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            vals = [
                (rng.standard_normal(len(p)) + 1j * rng.standard_normal(len(p))).astype(
                    np.complex64
                )
                for p in per
            ]
            re0, im0 = ex.pad_values(vals)
            best, err = time_chain(ex.trace_backward, ex.trace_forward, re0, im0, chain)
            record({
                "name": name, "dim": dim, "chain": chain,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
                "engaged": bool(getattr(ex, "_sparse_y", False)),
            })
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    CH = 48 if args.quick else 384
    CH32 = 256 if args.quick else 2048

    # ragged-all-to-all availability on this backend (UNBUFFERED's one-shot
    # transport; P=1 probe — multi-chip isn't attachable here)
    try:
        from spfft_tpu.parallel.ragged import _ragged_a2a_supported

        mesh1 = sp.make_fft_mesh(1)
        record({
            "name": "ragged_all_to_all_supported",
            "platform": dev.platform,
            "supported": bool(_ragged_a2a_supported(mesh1)),
        })
    except Exception as e:
        record({"name": "ragged_all_to_all_supported", "error": str(e)})

    # headline arms. NOTE: sparse-y is AUTO since the crossover landed, so
    # every arm not probing it pins SPFFT_TPU_SPARSE_Y explicitly to keep
    # one variable per arm.
    measure_local(
        "c2c_256_s15_baseline", 256, 0.659, CH, env={"SPFFT_TPU_SPARSE_Y": "0"}
    )
    measure_local(
        "c2c_256_s15_sparse_y", 256, 0.659, CH, env={"SPFFT_TPU_SPARSE_Y": "1"}
    )
    measure_local("c2c_256_s15_no_rotation", 256, 0.659, CH, no_rotation=True)
    # NOTE on arm names vs bench_results/round3_onchip.json (2026-07-31): that
    # batch ran BEFORE the pair-copy default flipped, so its "baseline" row is
    # pair-copy ON (8.44 ms) and its "no_pair_copy" row (6.88 ms) is what
    # "baseline" now measures. Current arms keep one variable per arm against
    # the current defaults.
    measure_local(
        "c2c_256_s15_pair_copy", 256, 0.659, CH,
        env={"SPFFT_TPU_PAIR_COPY": "1"},
    )

    # sparse-y crossover arms (the AUTO threshold's evidence, BASELINE.md
    # `sparse_y_crossover_256`): Sy/Y = 0.469 at 5% (wins), 0.562 at 9%
    # (wins), 0.688 at 15% (loses -> threshold 0.6)
    for pct, radius in (("5pct", 0.457), ("9pct", 0.55), ("15pct", 0.659)):
        for arm, sy in (("off", "0"), ("on", "1")):
            measure_local(
                f"sparse_y_{pct}_{arm}", 256, radius, CH,
                env={"SPFFT_TPU_SPARSE_Y": sy},
            )

    # copy-plan LANE width sweep (ROADMAP P2 settlement): 256 is noise-level,
    # 512 breaks the Z % LANE alignment precondition
    for lane in (256, 512):
        orig_lane = lanecopy.LANE
        lanecopy.LANE = lane
        try:
            measure_local(
                f"lane{lane}_c2c_256_s15", 256, 0.659, CH,
                env={"SPFFT_TPU_SPARSE_Y": "0"},
            )
        finally:
            lanecopy.LANE = orig_lane

    # Gauss 3-multiplication matmul A/B + f64 accuracy guard
    measure_local(
        "c2c_256_s15_classic_4mm", 256, 0.659, CH,
        env={"SPFFT_TPU_SPARSE_Y": "0", "SPFFT_TPU_GAUSS_MM": "0"},
    )
    # precision="high" speed tier (3-pass bf16; accuracy matrix below)
    measure_local(
        "precision_high_256_s15", 256, 0.659, CH,
        env={"SPFFT_TPU_SPARSE_Y": "0"}, precision="high",
    )
    # (the per-stage ablation rows come from programs/microbench_ablate.py)

    # precision x Gauss single-pair oracle accuracy matrix (128^3 on chip)
    try:
        dim128 = 128
        trip128 = sp.create_spherical_cutoff_triplets(dim128, dim128, dim128, 0.659)
        rng128 = np.random.default_rng(0)
        v128 = (
            rng128.standard_normal(len(trip128))
            + 1j * rng128.standard_normal(len(trip128))
        ).astype(np.complex64)
        dense128 = np.zeros((dim128,) * 3, dtype=np.complex128)
        dense128[trip128[:, 2], trip128[:, 1], trip128[:, 0]] = v128
        oracle128 = np.fft.ifftn(dense128) * dim128**3
        arms = {}
        for prec in ("highest", "high"):
            for gname, genv in (("gauss", "1"), ("classic", "0")):
                os.environ["SPFFT_TPU_GAUSS_MM"] = genv
                t128 = Transform(
                    ProcessingUnit.GPU, TransformType.C2C,
                    dim128, dim128, dim128,
                    indices=trip128, dtype=np.float32, precision=prec,
                )
                space = t128.backward(v128)
                arms[f"{prec}_{gname}"] = float(
                    np.abs(space - oracle128).max() / np.abs(oracle128).max()
                )
        os.environ.pop("SPFFT_TPU_GAUSS_MM", None)
        record({"name": "precision_oracle_matrix_128", "arms": arms})
    except Exception as e:
        record({"name": "precision_oracle_matrix_128", "error": f"{type(e).__name__}: {e}"})
    try:
        # f64 oracle accuracy under both matmul forms (32^3 C2C, CPU-exact
        # complex128 oracle) — the Gauss default's accuracy evidence
        import jax as _jax

        _prev_x64 = bool(_jax.config.read("jax_enable_x64"))
        _jax.config.update("jax_enable_x64", True)
        dim32 = 32
        trip32 = sp.create_spherical_cutoff_triplets(dim32, dim32, dim32, 1.1)
        rng32 = np.random.default_rng(0)
        v32 = rng32.standard_normal(len(trip32)) + 1j * rng32.standard_normal(
            len(trip32)
        )
        dense = np.zeros((dim32,) * 3, dtype=np.complex128)
        dense[trip32[:, 2], trip32[:, 1], trip32[:, 0]] = v32
        oracle = np.fft.ifftn(dense) * dim32**3
        accs = {}
        for arm, env in (("gauss", "1"), ("classic", "0")):
            os.environ["SPFFT_TPU_GAUSS_MM"] = env
            t32 = Transform(
                ProcessingUnit.GPU, TransformType.C2C, dim32, dim32, dim32,
                indices=trip32, dtype=np.float64,
            )
            space = t32.backward(v32)
            accs[arm] = float(np.abs(space - oracle).max() / np.abs(oracle).max())
        os.environ.pop("SPFFT_TPU_GAUSS_MM", None)
        record({"name": "f64_gauss_accuracy_32", **accs})
    except Exception as e:
        record({"name": "f64_gauss_accuracy_32", "error": f"{type(e).__name__}: {e}"})
    finally:
        # x64 mode must not leak into the later arms (one variable per arm)
        try:
            _jax.config.update("jax_enable_x64", _prev_x64)
        except NameError:
            pass

    # 32^3 long-chain re-measure (round-1 row was ~97% fixed tunnel cost)
    measure_local("c2c_32_dense", 32, 1.1, CH32)

    # P=1 distributed plan with the exchange specialized away
    measure_dist1("dist1_c2c_256_s15_specialized", 256, 0.659, CH)

    # distributed sparse-y A/B at the 5% cutoff (the stage's win case; same
    # names as the archived rows so a re-run refreshes them)
    for arm, sy in (("off", "0"), ("on", "1")):
        measure_dist1(
            f"dist1_5pct_sparse_y_{arm}", 256, 0.457, CH,
            env={"SPFFT_TPU_SPARSE_Y": sy},
        )

    # config-5 shape re-check (512^3 R2C 15% spherical) — shorter chain
    try:
        dim = 512
        trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
        xs = (trip[:, 0] >= 0) & (trip[:, 0] <= dim // 2)  # half-spectrum
        trip_r2c = trip[xs]
        t = Transform(
            ProcessingUnit.GPU, TransformType.R2C, dim, dim, dim,
            indices=trip_r2c, dtype=np.float32,
        )
        ex = t._exec
        rng = np.random.default_rng(0)
        n = len(trip_r2c)
        re0 = ex.put(rng.standard_normal(n).astype(np.float32))
        im0 = ex.put(rng.standard_normal(n).astype(np.float32))
        chain = 16 if args.quick else 96

        def body(carry, _):
            space = ex.trace_backward(*carry)
            return ex.trace_forward(space, None, ScalingType.FULL), None

        step = jax.jit(lambda r, i: jax.lax.scan(body, (r, i), None, length=chain)[0])
        wre, _ = step(re0, im0)
        float(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            cre, _ = step(re0, im0)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        record({
            "name": "r2c_512_sph15", "dim": 512, "chain": chain,
            "ms_per_pair": round(best * 1e3, 2),
            "gflops": round(flops_pair(512) / best / 1e9, 1),
        })
    except Exception as e:
        record({"name": "r2c_512_sph15", "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT} ({len(results)} rows)", file=sys.stderr)


if __name__ == "__main__":
    main()
