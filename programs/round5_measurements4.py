"""Round-5 on-chip batch 4: driver-config refresh + heuristic-boundary sweeps.

1. Re-pin the remaining driver configs with the round-5 engine: 32^3 dense
   C2C (config 1), 128^3 spherical C2C (config 2 class), R2C 128^3 dense
   (config 3).
2. Engagement-boundary sweeps so the promotion heuristics carry measured
   error bars (VERDICT r4 item 6, on-chip half): COPY_DENSE_FRAC
   {0.05, 0.1, 0.3} and SPARSE_Y_BLOCKED_FRAC {0.6, 0.8, 1.0} at the 256^3
   headline, one variable per arm.

Appends to bench_results/round5_onchip.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_onchip.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round5_measurements4", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import os

    import spfft_tpu as sp
    from spfft_tpu import (
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def flops_pair(dim):
        n = dim**3
        return 2 * 5.0 * n * np.log2(n)

    def chain_time(ex, re0, im0, chain, r2c=False):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                if r2c:
                    space = ex.trace_backward(carry[0], carry[1], phase=ph)
                    out = ex.trace_forward(space, None, ScalingType.FULL, phase=ph)
                else:
                    sre, sim = ex.trace_backward(*carry, phase=ph)
                    out = ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph)
                return out, None

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, _ = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, _ = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    def with_env(envs, fn):
        saved = {k: os.environ.get(k) for k in envs}
        for k, v in envs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            return fn()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def measure(name, trip, dim, ttype, chain, env=None):
        def run():
            t = Transform(
                ProcessingUnit.GPU, ttype, dim, dim, dim,
                indices=trip, dtype=np.float32, engine="mxu",
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            n = len(trip)
            re0 = ex.put(rng.standard_normal(n).astype(np.float32))
            im0 = ex.put(rng.standard_normal(n).astype(np.float32))
            best, err = chain_time(
                ex, re0, im0, chain, r2c=ttype == TransformType.R2C
            )
            record({
                "name": name, "dim": dim, "chain": chain,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
            })

        try:
            with_env(env or {}, run)
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"[:300]})

    C2C, R2C = TransformType.C2C, TransformType.R2C

    # ---- 1: remaining driver configs ----
    dim = 32
    xs, ys, zs = np.meshgrid(*[np.arange(dim)] * 3, indexing="ij")
    dense32 = np.stack([xs.ravel(), ys.ravel(), zs.ravel()], 1).astype(np.int64)
    measure("c2c_32_dense_r5", dense32, 32, C2C, 2048)

    trip128 = sp.create_spherical_cutoff_triplets(128, 128, 128, 0.659)
    measure("c2c_128_sph15_r5", trip128, 128, C2C, 768)

    xs, ys, zs = np.meshgrid(
        np.arange(64 + 1), np.arange(128), np.arange(128), indexing="ij"
    )
    keep = ~((xs == 0) & (ys > 64))
    r2c128 = np.stack([xs[keep].ravel(), ys[keep].ravel(), zs[keep].ravel()], 1)
    measure("r2c_128_dense_r5", r2c128, 128, R2C, 512)

    # ---- 2: heuristic boundary sweeps at the 256^3 headline ----
    trip256 = sp.create_spherical_cutoff_triplets(256, 256, 256, 0.659)
    for frac in ("0.05", "0.1", "0.3"):
        measure(
            f"c2c_256_s15_r5_densefrac{frac}", trip256, 256, C2C, 384,
            env={"SPFFT_TPU_COPY_DENSE_FRAC": frac},
        )
    for frac in ("0.6", "0.8", "1.0"):
        measure(
            f"c2c_256_s15_r5_blockedfrac{frac}", trip256, 256, C2C, 384,
            env={"SPFFT_TPU_SPARSE_Y_BLOCKED_FRAC": frac},
        )

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
