"""Run a self-verified transform roundtrip and print a verification report.

The ABFT surface CLI (spfft_tpu.verify): builds a plan with verification
armed, runs a backward+forward(FULL) roundtrip — optionally under fault
injection (``--inject``) to demonstrate detect -> retry -> demote -> recover
— and emits a JSON report: the plan card's schema-pinned ``verification``
section, the roundtrip residual against the input values (FULL scaling makes
the pair an identity, so the residual is an end-to-end correctness witness
that holds *through* any recovery), the verify-layer metrics, and the engine
circuit-breaker state. Exit status: 0 on a verified (possibly recovered)
roundtrip, 3 when verification raised typed ``VerificationError``.

Usage:
    python programs/verify.py -d 16 16 16                       # clean run
    python programs/verify.py -d 16 16 16 --inject "engine.execute=corrupt:1.0"
    python programs/verify.py -d 16 16 16 --mode strict --inject "engine.execute=nan"
    python programs/verify.py -d 32 32 32 --shards 2 -o report.json
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-d", nargs=3, type=int, default=[16, 16, 16],
                    metavar=("X", "Y", "Z"))
    ap.add_argument("-s", type=float, default=0.3, help="nonzero fraction")
    ap.add_argument("--mode", default="on", choices=["on", "strict"])
    ap.add_argument("--shards", type=int, default=1,
                    help="1-D slab mesh width (1 = local plan)")
    ap.add_argument("--inject", default=None,
                    help='fault spec to arm, e.g. "engine.execute=corrupt:1.0"')
    ap.add_argument("--roundtrips", type=int, default=1,
                    help="verified roundtrips to run (breaker demos need > K)")
    ap.add_argument("-o", default=None, help="write the report JSON here")
    args = ap.parse_args(argv)

    if args.shards > 1:
        from spfft_tpu.parallel.mesh import ensure_virtual_devices

        ensure_virtual_devices(args.shards, warn=True, platform="cpu")

    import spfft_tpu as sp
    from spfft_tpu import (
        ProcessingUnit,
        ScalingType,
        TransformType,
        VerificationError,
        faults,
        obs,
    )

    dx, dy, dz = args.d
    radius = sp.spherical_radius_for_fraction(args.s)
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, min(radius, 1.0))
    rng = np.random.default_rng(0)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))

    if args.inject:
        faults.arm(args.inject)

    if args.shards > 1:
        mesh = sp.make_fft_mesh(args.shards)
        plan = sp.DistributedTransform(
            ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz, trip,
            mesh=mesh, verify=args.mode,
        )
        # re-pack the global values into the plan's per-shard order
        from spfft_tpu.parameters import distribute_triplets

        shards_trip = distribute_triplets(trip, args.shards, dy)
        lut = {tuple(t): v for t, v in zip(map(tuple, trip), values)}
        per_shard = [
            np.asarray([lut[tuple(t)] for t in s]) for s in shards_trip
        ]
        run = lambda: (  # noqa: E731
            plan.backward([v.copy() for v in per_shard]),
            plan.forward(scaling=ScalingType.FULL),
        )
        packed = np.concatenate(per_shard)
        repack = lambda out: np.concatenate([np.asarray(v) for v in out])  # noqa: E731
    else:
        plan = sp.Transform(
            ProcessingUnit.HOST, TransformType.C2C, dx, dy, dz,
            indices=trip, verify=args.mode,
        )
        run = lambda: (plan.backward(values), plan.forward(scaling=ScalingType.FULL))  # noqa: E731
        packed = values
        repack = np.asarray

    report: dict = {"mode": args.mode, "injected": args.inject}
    status = 0
    residual = None
    try:
        for _ in range(max(1, args.roundtrips)):
            space, back = run()
        residual = float(
            np.max(np.abs(repack(back) - packed)) / np.max(np.abs(packed))
        )
        report["outcome"] = "verified"
        report["roundtrip_residual"] = residual
    except VerificationError as e:
        report["outcome"] = "verification_error"
        report["error"] = str(e)
        status = 3

    card = plan.report()
    snap = obs.snapshot()
    report["verification"] = card["verification"]
    report["degradations"] = card["degradations"]
    report["run_id"] = card["run_id"]
    report["metrics"] = {
        k: v for k, v in snap["counters"].items() if k.startswith("verify")
    }
    report["breaker"] = sp.verify.breaker.snapshot()
    missing = obs.validate_plan_card(card)
    if missing:
        report["card_schema_missing"] = missing
        status = status or 1

    print(json.dumps(report, indent=2))
    if args.o:
        Path(args.o).write_text(json.dumps(report, indent=2) + "\n")
    return status


if __name__ == "__main__":
    sys.exit(main())
