"""Microbench round 2: precision tiers, fixed four-step, radix-2 hybrid.

!! TIMING METHODOLOGY SUPERSEDED: this harness times independent repeats with
jax.block_until_ready, which neither prevents XLA from hoisting loop-invariant
work nor fences execution on the tunneled axon TPU. Numbers from it are
unreliable; use the dependent-chain + scalar-fetch methodology of
programs/microbench_ablate.py / microbench_pallas.py instead. Kept for the
record of which variants were explored. (The direct-matmul-DFT design choice it
informed was re-validated with correct timing: see BASELINE.md "Four-step
factored DFT".)
"""
from __future__ import annotations

import argparse
import time

import numpy as np

import jax
import jax.numpy as jnp


def dft_matrix(n, sign=+1, dtype=np.float32):
    k = np.arange(n)
    w = np.exp(sign * 2j * np.pi * np.outer(k, k) / n)
    return jnp.asarray(w.real.astype(dtype)), jnp.asarray(w.imag.astype(dtype))


def make_cmatmul(precision):
    def cmatmul(xr, xi, wr, wi, spec):
        yr = jnp.einsum(spec, xr, wr, precision=precision) - jnp.einsum(
            spec, xi, wi, precision=precision
        )
        yi = jnp.einsum(spec, xr, wi, precision=precision) + jnp.einsum(
            spec, xi, wr, precision=precision
        )
        return yr, yi

    return cmatmul


def make_direct(n, dtype, precision):
    wr, wi = dft_matrix(n, dtype=dtype)
    cm = make_cmatmul(precision)
    return jax.jit(lambda xr, xi: cm(xr, xi, wr, wi, "bn,nk->bk"))


def make_ct(n, n1, dtype, precision):
    """Four-step, correct index math: x[j1*n2+j2]; DFT over j1 -> k1; twiddle
    W^{k1 j2}; DFT over j2 -> k2; out[k] = X[k1 + n1*k2]."""
    n2 = n // n1
    w1r, w1i = dft_matrix(n1, dtype=dtype)
    w2r, w2i = dft_matrix(n2, dtype=dtype)
    k1, j2 = np.arange(n1), np.arange(n2)
    tw = np.exp(2j * np.pi * np.outer(k1, j2) / n)
    twr, twi = jnp.asarray(tw.real.astype(dtype)), jnp.asarray(tw.imag.astype(dtype))
    cm = make_cmatmul(precision)

    def f(xr, xi):
        xr_ = xr.reshape(-1, n1, n2)
        xi_ = xi.reshape(-1, n1, n2)
        yr, yi = cm(xr_, xi_, w1r, w1i, "bjn,jk->bkn")  # DFT over j1 -> k1
        zr = yr * twr - yi * twi
        zi = yr * twi + yi * twr
        or_, oi_ = cm(zr, zi, w2r, w2i, "bkn,nm->bkm")  # DFT over j2 -> k2
        # X[k1, k2] flat index k1 + n1*k2 -> row-major order is (k2, k1)
        return (
            or_.transpose(0, 2, 1).reshape(-1, n),
            oi_.transpose(0, 2, 1).reshape(-1, n),
        )

    return jax.jit(f)


def make_radix2(n, dtype, precision):
    """One DIF radix-2 butterfly (VPU) + two half-size DFT matmuls.
    X[2k]  = DFT_{n/2}(x[j] + x[j+n/2])
    X[2k+1]= DFT_{n/2}((x[j] - x[j+n/2]) * W^j),  W = exp(+2i pi / n)."""
    h = n // 2
    whr, whi = dft_matrix(h, dtype=dtype)
    j = np.arange(h)
    tw = np.exp(2j * np.pi * j / n)
    twr, twi = jnp.asarray(tw.real.astype(dtype)), jnp.asarray(tw.imag.astype(dtype))
    cm = make_cmatmul(precision)

    def f(xr, xi):
        ar, ai = xr[:, :h], xi[:, :h]
        br, bi = xr[:, h:], xi[:, h:]
        er, ei = ar + br, ai + bi
        dr, di = ar - br, ai - bi
        odr = dr * twr - di * twi
        odi = dr * twi + di * twr
        # batch the two half-DFTs together as one matmul
        sr = jnp.concatenate([er, odr], axis=0)
        si = jnp.concatenate([ei, odi], axis=0)
        yr, yi = cm(sr, si, whr, whi, "bn,nk->bk")
        b = xr.shape[0]
        out_r = jnp.stack([yr[:b], yr[b:]], axis=-1).reshape(b, n)
        out_i = jnp.stack([yi[:b], yi[b:]], axis=-1).reshape(b, n)
        return out_r, out_i

    return jax.jit(f)


def timeit(f, args, reps):
    out = f(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ns", default="128,256,512")
    ap.add_argument("--reps", type=int, default=20)
    args = ap.parse_args()
    dtype = np.dtype("float32")
    P = jax.lax.Precision

    rng = np.random.default_rng(0)
    for n in [int(x) for x in args.ns.split(",")]:
        batch = n * n
        xr = jnp.asarray(rng.standard_normal((batch, n)).astype(dtype))
        xi = jnp.asarray(rng.standard_normal((batch, n)).astype(dtype))
        ref = np.fft.ifft(np.asarray(xr) + 1j * np.asarray(xi), axis=-1) * n

        cands = {
            "direct/HIGHEST": make_direct(n, dtype, P.HIGHEST),
            "direct/HIGH": make_direct(n, dtype, P.HIGH),
            "radix2/HIGHEST": make_radix2(n, dtype, P.HIGHEST),
            "radix2/HIGH": make_radix2(n, dtype, P.HIGH),
        }
        if n == 256:
            cands["ct16x16/HIGHEST"] = make_ct(n, 16, dtype, P.HIGHEST)
            cands["ct2x128/HIGHEST"] = make_ct(n, 2, dtype, P.HIGHEST)
        if n == 512:
            cands["ct4x128/HIGHEST"] = make_ct(n, 4, dtype, P.HIGHEST)
            cands["ct4x128/HIGH"] = make_ct(n, 4, dtype, P.HIGH)

        for name, f in cands.items():
            rr, ri = f(xr, xi)
            err = np.max(np.abs((np.asarray(rr) + 1j * np.asarray(ri)) - ref)) / np.max(
                np.abs(ref)
            )
            t = timeit(f, (xr, xi), args.reps)
            print(f"N={n:4d} {name:18s} {t*1e3:8.3f} ms  rel_err={err:.2e}")


if __name__ == "__main__":
    main()
