"""Distributed benchmark: multichip strong/weak-scaling perf rows.

The distributed counterpart of ``bench.py`` (ROADMAP item 1: a multichip
GFLOP/s number, not just a dryrun ok-flag): builds slab (1-D) and 2-D pencil
plans across a device-count ladder (real accelerators when enough are
attached, virtual CPU devices otherwise), measures each with the shared
fenced best-of-R chained-roundtrip discipline
(``spfft_tpu.obs.perf.measure_pair_seconds`` — the ``tuning/runner.py``
warmup/best-of rules plus ``bench.py``'s dispatch-amortizing ``lax.scan``
chain), and emits one ``spfft_tpu.obs.perf/1`` report per cell: per-stage
seconds, GFLOP/s, GB/s and the ``exchange_fraction`` scoreboard, joined to
the plan card and flight recorder by run ID.

Strong-scaling rows keep the grid fixed as devices grow; weak-scaling rows
grow ``dim_z`` with the device count (constant per-device volume). The
multi-row JSON document (schema ``spfft_tpu.obs.perf.scaling/1``,
``obs.perf.validate_scaling_doc``) is the format that replaces the bare
ok-flag MULTICHIP captures, and is what ``programs/perf_gate.py`` gates
against a committed baseline (``./ci.sh perf``).

Usage:
    python programs/dbench.py --devices 1 2 4 8 --dim 32 -o MULTICHIP.json
    python programs/dbench.py --devices 8 --mesh pencil --scaling weak
    python programs/dbench.py --devices 4 --r2c --dtype f64 --engine xla
    python programs/dbench.py --devices 8 --overlap 1 4   # OVERLAPPED rows

``--overlap`` measures each cell once per requested OVERLAPPED-discipline
chunk count (keys carry an ``ovC`` token); the stdout table prints each
row's best-vs-median repeat spread (the ``±`` column — the same
``seconds_noise`` the gate widens its threshold by), so a single bad repeat
is visible at capture time instead of poisoning a committed baseline.

On a CPU mesh the wall-clock is indicative only (collectives are memory
copies); run on a pod slice for decision-grade rows — the report schema and
the gate are identical either way.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def row_key(report: dict, scaling: str) -> str:
    """Stable scenario key a gate matches rows on: everything that defines
    the cell except the measured numbers (the effective overlap chunk count
    included, so overlapped and bulk-synchronous rows gate side by side)."""
    dims = "x".join(str(d) for d in report["dims"])
    return (
        f"{scaling}:{report['decomposition']}:P{report['device_count']}"
        f":{dims}:{report['transform_type']}:{report['dtype']}"
        f":{report['exchange_discipline']}:{report['engine']}"
        f":nnz{report['nnz_fraction']:.3f}"
        f":ov{report.get('overlap_chunks', 1)}"
    )


def build_transform(args, mesh_kind, devices, dims, mesh_devices, overlap=1):
    """One plan for a scaling cell (slab or pencil over ``devices`` chips)."""
    import numpy as np

    import spfft_tpu as sp
    from spfft_tpu import ExchangeType, ProcessingUnit, TransformType

    dx, dy, dz = dims
    radius = sp.spherical_radius_for_fraction(args.sparsity)
    trip = sp.create_spherical_cutoff_triplets(
        dx, dy, dz, min(radius, 1.0), hermitian_symmetry=args.r2c
    )
    ttype = TransformType.R2C if args.r2c else TransformType.C2C
    dtype = np.float64 if args.dtype == "f64" else np.float32
    pu = ProcessingUnit.GPU if args.engine == "mxu" else ProcessingUnit.HOST
    if devices == 1 and mesh_kind == "slab" and not args.force_mesh:
        # the P=1 rung is the local plan — the honest single-chip anchor of
        # a strong-scaling curve (a 1-wide mesh adds sharding machinery)
        return sp.Transform(
            pu, ttype, dx, dy, dz, indices=trip, dtype=dtype,
            engine=args.engine,
        )
    if mesh_kind == "pencil":
        mesh = sp.make_fft_mesh2(2, devices // 2, devices=mesh_devices)
    else:
        mesh = sp.make_fft_mesh(devices=mesh_devices)
    return sp.DistributedTransform(
        pu, ttype, dx, dy, dz, trip, mesh=mesh, dtype=dtype,
        engine=args.engine, exchange_type=ExchangeType[args.exchange],
        overlap=overlap,
    )


def measure_row(transform, args, scaling: str) -> dict:
    """Measure one cell and wrap it as a keyed scaling row (a validating
    perf report plus the scenario key and a noise figure for the gate)."""
    from spfft_tpu.obs import perf

    m = perf.measure_pair_seconds(
        transform, chain=args.chain, repeats=args.repeats, warmup=args.warmup
    )
    if m["roundtrip_residual"] is not None and m["roundtrip_residual"] > 1e-2:
        raise AssertionError(
            f"roundtrip chain diverged: {m['roundtrip_residual']}"
        )
    row = perf.perf_report(
        transform, m["seconds_per_pair"], repeats=m["repeats"]
    )
    best = m["seconds_per_pair"]
    row["scaling"] = scaling
    row["rep_seconds"] = m["rep_seconds"]
    # relative spread of the timed repeats (median vs best — one outlier
    # repeat must not blow the figure up; even counts average the middle
    # pair, so repeats=2 records half the spread, not the full range): the
    # gate widens its threshold by this, capped, so a noisy host cannot fake
    # a regression
    reps = sorted(m["rep_seconds"])
    median = (reps[(len(reps) - 1) // 2] + reps[len(reps) // 2]) / 2.0
    row["seconds_noise"] = (median - best) / best if best else 0.0
    # the per-row parity check: a diverged chain never becomes a row (the
    # assertion above); the residual itself rides along so a committed
    # capture shows each row's roundtrip health (None for R2C)
    row["roundtrip_residual"] = m["roundtrip_residual"]
    row["key"] = row_key(row, scaling)
    return row


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--devices", type=int, nargs="+", default=[1, 2, 4, 8],
                    help="device-count ladder (virtual CPU devices stand in "
                    "when the host has fewer real chips)")
    ap.add_argument("--dim", type=int, default=32,
                    help="strong-scaling grid edge (weak rows scale dim_z)")
    ap.add_argument("--sparsity", type=float, default=0.15,
                    help="nonzero fraction of the frequency ball")
    ap.add_argument("--mesh", nargs="+", default=["slab", "pencil"],
                    choices=["slab", "pencil"])
    ap.add_argument("--scaling", nargs="+", default=["strong", "weak"],
                    choices=["strong", "weak"])
    ap.add_argument("--engine", default="mxu", choices=["xla", "mxu"])
    ap.add_argument("--exchange", default="DEFAULT",
                    help="exchange discipline name (DEFAULT = policy pick)")
    ap.add_argument("--r2c", action="store_true")
    ap.add_argument("--dtype", default="f32", choices=["f32", "f64"])
    ap.add_argument("--overlap", type=int, nargs="+", default=[1],
                    help="OVERLAPPED-discipline chunk counts to measure per "
                    "cell (1 = bulk-synchronous; engines clamp infeasible "
                    "requests and duplicate-clamped cells are skipped)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--chain", type=int, default=4)
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--cpu", action="store_true",
                    help="force the virtual CPU platform (CI uses this; the "
                    "default consults attached accelerators when safe)")
    ap.add_argument("--force-mesh", action="store_true",
                    help="run P=1 through the distributed machinery too")
    ap.add_argument("-o", default=None, help="write the scaling JSON here")
    args = ap.parse_args(argv)

    if min(args.devices) < 1:
        ap.error("--devices must be positive")

    # device bootstrap before the first backend touch (virtual CPU fallback)
    from spfft_tpu.parallel.mesh import ensure_virtual_devices

    max_p = max(args.devices)
    all_devices = ensure_virtual_devices(
        max_p, warn=True, platform="cpu" if args.cpu else None
    )

    import jax

    if args.dtype == "f64" and not jax.config.read("jax_enable_x64"):
        jax.config.update("jax_enable_x64", True)

    from spfft_tpu.obs import perf

    rows = []
    for scaling in args.scaling:
        for P in sorted(set(args.devices)):
            dims = (args.dim, args.dim, args.dim * P if scaling == "weak"
                    else args.dim)
            for mesh_kind in args.mesh:
                if mesh_kind == "pencil" and (P < 4 or P % 2):
                    # 2 x (P/2) pencil factorization needs P >= 4, even —
                    # say so: a silently empty sweep must not look clean
                    print(f"note: skipping pencil at P={P} "
                          "(needs an even device count >= 4)", file=sys.stderr)
                    continue
                seen_ov = set()
                for overlap in sorted(set(args.overlap)):
                    t = build_transform(
                        args, mesh_kind, P, dims, all_devices[:P],
                        overlap=overlap,
                    )
                    effective = int(getattr(t, "overlap_chunks", 1))
                    if effective in seen_ov:
                        # the engine clamped this request onto a chunk count
                        # already measured (P=1 local rung, tiny extents) —
                        # a duplicate key row would shadow the first
                        continue
                    seen_ov.add(effective)
                    row = measure_row(t, args, scaling)
                    rows.append(row)
                    print(
                        f"{scaling:6s} {mesh_kind:6s} P={P:2d} "
                        f"{'x'.join(str(d) for d in dims):>12s} ov={effective:2d} "
                        f"{row['seconds_per_pair'] * 1e3:9.3f} ms/pair "
                        f"±{row['seconds_noise'] * 100:5.1f}% "
                        f"{row['gflops']:9.2f} GFLOP/s "
                        f"exch {row['exchange_fraction'] * 100:5.1f}% "
                        f"({row['exchange_gbps']:.2f} GB/s wire)"
                    )

    if not rows:
        # every cell was skipped: exiting 0 with an empty document would
        # read as a clean bench run that never happened
        print("dbench: no measurable cells for the requested "
              "devices/mesh/scaling combination", file=sys.stderr)
        return 1

    platform = str(all_devices[0].platform)
    doc = {
        "schema": perf.SCALING_SCHEMA,
        "config": {k: v for k, v in vars(args).items() if k != "o"},
        "platform": platform,
        "rows": rows,
    }
    missing = perf.validate_scaling_doc(doc)
    if args.o:
        Path(args.o).write_text(json.dumps(doc, indent=2) + "\n")
        print(f"wrote {len(rows)} rows to {args.o}")
    if missing:
        print(f"scaling doc INCOMPLETE, missing: {missing}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
