"""Graph-scheduling benchmark: scheduled vs one-at-a-time transform graphs.

The measurement surface of :mod:`spfft_tpu.sched` (ROADMAP item 4): builds a
**mixed-geometry graph workload** — several distinct sparse-FFT geometries,
multiple independent executions each, plus one backward->forward dependency
chain per geometry — and runs it on the multichip mesh (virtual CPU devices
stand in off-pod) two ways:

- ``serial`` — one-at-a-time submission: every task pays its own dispatch,
  completion fence and host fetch before the next starts (the baseline the
  acceptance bar compares against);
- ``sched`` — the task-graph executor: windowed dispatch keeps transforms in
  flight, host staging of one task hides behind device execution of others,
  finalize runs in completion order, and placement spreads plans across the
  mesh (model round-robin by default; ``--policy tuned`` resolves the width
  through wisdom trials).

Both modes execute the *same* task list through the *same* plan objects'
code paths, so the ratio is the scheduler's contribution alone. Output rows
are **gate-compatible** with ``programs/perf_gate.py`` (``key`` /
``gflops`` / ``seconds_noise``, like dbench and loadgen rows) plus the
scheduling scoreboard: completed transforms/sec, p50/p99 per-task
completion latency within the cycle, and ``overlap_vs_serial`` (scheduled
throughput over serial throughput — the headline; >1 means the graph
overlap is real). ``GBENCH_r09.json`` is the first committed capture;
``./ci.sh sched`` runs a smoke configuration and gates it against
``bench_results/gbench_baseline_cpu8.json``.

On a CPU mesh the wall-clock is indicative (devices share host cores); the
serial-vs-scheduled *ratio* is the robust figure — both modes see the same
host.

Usage:
    python programs/gbench.py --devices 8 -o GBENCH.json
    python programs/gbench.py --devices 8 --dims 12 16 --tasks 6 --policy tuned
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

GBENCH_SCHEMA = "spfft_tpu.sched.gbench/1"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--devices", type=int, default=8,
                   help="mesh width (virtual CPU devices stand in off-pod)")
    p.add_argument("--dims", type=int, nargs="+", default=[12, 16, 20],
                   help="grid edges of the mixed geometries")
    p.add_argument("--sparsity", type=float, nargs="+", default=[0.5, 0.9],
                   help="nnz sphere radii paired round-robin with --dims")
    p.add_argument("--tasks", type=int, default=8,
                   help="independent backward tasks per geometry")
    p.add_argument("--chain", type=int, default=1,
                   help="backward->forward dependency chains per geometry "
                   "(exercises graph edges; 0 = flat batch)")
    p.add_argument("--repeats", type=int, default=3,
                   help="timed repeats per mode (best-of, spread recorded)")
    p.add_argument("--inflight", type=int, default=16,
                   help="scheduler window (~2 per device keeps queues fed; "
                   "None defers to SPFFT_TPU_SCHED_INFLIGHT)")
    p.add_argument("--policy", choices=["default", "tuned"], default="default",
                   help="placement policy: model round-robin or wisdom-"
                   "tuned width (tuned allows CPU trials implicitly here)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("-o", "--output", default=None, help="write JSON here")
    return p


def build_workload(args):
    """The task list: per geometry, ``--tasks`` independent backwards plus
    ``--chain`` backward->forward chains. Returns (geometries, tasks) where
    each task is a JSON-plain dict the two run modes share."""
    import numpy as np
    import spfft_tpu as sp

    rng = np.random.default_rng(args.seed)
    geometries = []
    for i, dim in enumerate(args.dims):
        sparsity = args.sparsity[i % len(args.sparsity)]
        trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
        vals = (
            rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
        )
        geometries.append({
            "dim": dim, "sparsity": sparsity, "triplets": trip,
            "values": vals,
            "spec": {
                "transform_type": "C2C",
                "dims": (dim, dim, dim),
                "indices": trip,
            },
        })
    tasks = []
    for gi, g in enumerate(geometries):
        for t in range(args.tasks):
            tasks.append({"geom": gi, "direction": "backward", "chain": None,
                          "id": f"g{gi}b{t}"})
        for c in range(args.chain):
            bid = f"g{gi}cb{c}"
            tasks.append({"geom": gi, "direction": "backward", "chain": None,
                          "id": bid})
            tasks.append({"geom": gi, "direction": "forward", "chain": bid,
                          "id": f"g{gi}cf{c}"})
    return geometries, tasks


def run_serial(geometries, tasks, plans) -> dict:
    """One-at-a-time submission: each task is a full host-facing
    ``backward``/``forward`` call (dispatch + fence + fetch) before the next
    starts. ``plans[geom]`` is the single per-geometry plan, all on one
    device — exactly how a caller without the scheduler would submit."""
    from spfft_tpu.types import ScalingType

    t0 = time.perf_counter()
    latencies = []
    chained = {}
    for task in tasks:
        g = geometries[task["geom"]]
        plan = plans[task["geom"]]
        s0 = time.perf_counter()
        if task["direction"] == "backward":
            out = plan.backward(g["values"])
            chained[task["id"]] = out
        else:
            plan.forward(chained[task["chain"]], ScalingType.FULL)
        latencies.append(time.perf_counter() - s0)
    return {"wall": time.perf_counter() - t0, "latencies": latencies}


def run_sched(geometries, tasks, devices, pool, args) -> tuple:
    """The same task list as one :class:`~spfft_tpu.sched.TaskGraph`."""
    from spfft_tpu import sched
    from spfft_tpu.types import ScalingType

    graph = sched.TaskGraph()
    for task in tasks:
        g = geometries[task["geom"]]
        if task["direction"] == "backward":
            graph.add("backward", id=task["id"], payload=g["values"],
                      spec=g["spec"])
        else:
            graph.add("forward", id=task["id"], scaling=ScalingType.FULL,
                      spec=g["spec"], input_from=task["chain"])
    # monotonic, NOT perf_counter: per-task latencies subtract this origin
    # from Task.finished_at, which the executor stamps with time.monotonic()
    t0 = time.monotonic()
    report = sched.run_graph(
        graph, devices=devices, pool=pool,
        policy=args.policy if args.policy == "tuned" else None,
        max_inflight=args.inflight,
    )
    wall = time.monotonic() - t0
    bad = {
        tid: out for tid, out in report.outcomes.items()
        if out not in ("completed", "demoted")
    }
    if bad:
        raise AssertionError(f"scheduled tasks did not complete: {bad}")
    latencies = [
        graph.task(t["id"]).finished_at - t0 for t in tasks
    ]
    return {"wall": wall, "latencies": latencies}, report, graph


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def make_row(key, measures, tasks, flops_total, depth) -> dict:
    """Gate-compatible row from best-of-repeat measures of one mode.
    Latencies come from the best-WALL repeat, so p50/p99 and the
    throughput figure describe the same run."""
    walls = sorted(m["wall"] for m in measures)
    best = walls[0]
    median = (walls[(len(walls) - 1) // 2] + walls[len(walls) // 2]) / 2.0
    lat = sorted(min(measures, key=lambda m: m["wall"])["latencies"])
    return {
        "key": key,
        "tasks": len(lat),
        "graph_depth": depth,
        "wall_seconds": round(best, 6),
        "transforms_per_sec": round(len(lat) / best, 3) if best else 0.0,
        "p50_ms": round(_percentile(lat, 0.50) * 1e3, 3),
        "p99_ms": round(_percentile(lat, 0.99) * 1e3, 3),
        "gflops": round(flops_total / best / 1e9, 6) if best else 0.0,
        "seconds_noise": round((median - best) / best, 4) if best else 0.0,
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import os

    if args.policy == "tuned":
        # tuned placement measures on this same mesh; CPU trials cannot
        # poison accelerator wisdom (platform is in the key) — same rule as
        # discipline_compare's tuned cells
        os.environ.setdefault("SPFFT_TPU_TUNE_CPU", "1")
    from spfft_tpu.parallel.mesh import ensure_virtual_devices

    devices = list(ensure_virtual_devices(max(1, args.devices), warn=True))
    devices = devices[: max(1, args.devices)]

    from spfft_tpu import obs, sched
    from spfft_tpu.obs import perf
    from spfft_tpu.sched.placement import build_plan

    geometries, tasks = build_workload(args)
    flops_total = sum(
        perf.dense_pair_flops([geometries[t["geom"]]["dim"]] * 3) / 2.0
        for t in tasks
    )
    # plan pools built OUTSIDE the measured window (both modes measure
    # execution, not construction): serial gets one plan per geometry on
    # device 0; sched resolves through the placement pass + pool
    serial_plans = [build_plan(g["spec"], devices[0]) for g in geometries]
    pool = sched.PlanPool()

    # warmup: one untimed pass per mode absorbs compilation everywhere
    run_serial(geometries, tasks, serial_plans)
    _, report, last_graph = run_sched(geometries, tasks, devices, pool, args)

    serial_measures = [
        run_serial(geometries, tasks, serial_plans)
        for _ in range(max(1, args.repeats))
    ]
    sched_measures = []
    for _ in range(max(1, args.repeats)):
        m, report, last_graph = run_sched(geometries, tasks, devices, pool, args)
        sched_measures.append(m)

    sig = "+".join(
        f"{g['dim']}s{int(round(g['sparsity'] * 100))}" for g in geometries
    )
    base = f"gbench:{sig}:t{args.tasks}:c{args.chain}:P{len(devices)}"
    depth = 2 if args.chain else 1
    serial_row = make_row(
        f"{base}:serial", serial_measures, tasks, flops_total, depth
    )
    sched_row = make_row(
        f"{base}:sched", sched_measures, tasks, flops_total, depth
    )
    serial_row["overlap_vs_serial"] = 1.0
    sched_row["overlap_vs_serial"] = round(
        sched_row["transforms_per_sec"]
        / max(serial_row["transforms_per_sec"], 1e-9),
        4,
    )
    for row in (serial_row, sched_row):
        print(
            f"{row['key']}: {row['transforms_per_sec']:8.1f} transforms/s "
            f"(p50 {row['p50_ms']:.2f} ms, p99 {row['p99_ms']:.2f} ms, "
            f"±{row['seconds_noise'] * 100:.1f}%, "
            f"x{row['overlap_vs_serial']:.2f} vs serial)"
        )

    doc = {
        "schema": GBENCH_SCHEMA,
        "run_unix": time.time(),
        "platform": str(devices[0].platform),
        "config": {
            "devices": len(devices), "dims": list(args.dims),
            "sparsity": list(args.sparsity), "tasks": args.tasks,
            "chain": args.chain, "repeats": args.repeats,
            "policy": args.policy, "inflight": args.inflight,
            "seed": args.seed, "total_tasks": len(tasks),
        },
        "rows": [serial_row, sched_row],
        # full provenance: the placement record of the measured graph run,
        # plus one PLACED plan's card extract per geometry, taken from the
        # measured graph itself (run-ID join + the card's schema-pinned
        # placement section; pool.plan_for here could build a fresh,
        # never-placed plan and report a null section)
        "placement": report.placement,
        "plan_cards": [
            {
                "run_id": c["run_id"],
                "engine": c["engine"],
                "dims": c["dims"],
                "placement": c.get("placement"),
            }
            for c in (
                last_graph.task(
                    next(t["id"] for t in tasks if t["geom"] == gi)
                ).plan.report()
                for gi in range(len(geometries))
                if any(t["geom"] == gi for t in tasks)
            )
        ],
        "metrics": {
            k: v for k, v in obs.snapshot()["counters"].items()
            if k.startswith("sched_")
        },
    }

    if args.output:
        Path(args.output).write_text(json.dumps(doc, indent=1, sort_keys=True))
        print(f"wrote {args.output}")
    else:
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
