"""Open-loop load generator for the serving layer (`spfft_tpu.serve`).

Drives sustained multi-tenant traffic against a :class:`TransformService`
the way a fleet of independent callers would: arrivals are scheduled on a
fixed offered-rate clock and submitted WITHOUT waiting for completions
(open-loop — offered load does not slow down when the service does, which
is exactly what makes overload visible; a closed loop self-throttles and
hides it). Each ramp step multiplies the offered rate, so one run sweeps
from comfortable load into deliberate overload and records how the service
degrades: typed rejections and sheds instead of latency collapse.

Output: a JSON report (schema ``spfft_tpu.serve.loadgen/1``) whose rows are
**gate-compatible** with ``programs/perf_gate.py`` (``key`` / ``gflops`` /
``seconds_noise``, like dbench scaling rows) plus the serving scoreboard
fields: offered/accepted/completed/rejected/shed/deadline-miss counts,
completed transforms/sec, and p50/p99 latency ms. ``SERVE_r08.json`` is the
first committed capture; ``./ci.sh serve`` runs a smoke and an overload
configuration of this CLI.

GFLOP/s accounting: each completed transform is billed the dense one-
direction flop count (``perf.dense_pair_flops(dims) / 2``) — comparable
across loadgen rows with the same key, which is all the regression gate
compares. ``seconds_noise`` is the relative p50→p99 latency spread, capped
at 0.5, so the gate's noise-aware allowance widens on jittery hosts the
same way dbench's repeat spread does.

Usage:
    python programs/loadgen.py -d 16 16 16 -s 0.8 --tenants 2 \
        --rate 50 --ramp 1 2 4 --duration 2 -o loadgen.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import numpy as np

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

LOADGEN_SCHEMA = "spfft_tpu.serve.loadgen/1"


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("-d", "--dims", type=int, nargs=3, default=[16, 16, 16],
                   metavar=("X", "Y", "Z"))
    p.add_argument("-s", "--sparsity", type=float, default=0.8,
                   help="spherical-cutoff radius fraction (triplet density)")
    p.add_argument("--tenants", type=int, default=2)
    p.add_argument("--rate", type=float, default=50.0,
                   help="offered requests/sec at ramp multiplier 1")
    p.add_argument("--ramp", type=float, nargs="+", default=[1.0, 2.0],
                   help="offered-rate multipliers, one measured row each")
    p.add_argument("--duration", type=float, default=2.0,
                   help="seconds of offered traffic per ramp step")
    p.add_argument("--timeout-s", type=float, default=0.0,
                   help="per-request deadline (0 = none)")
    p.add_argument("--queue-cap", type=int, default=None)
    p.add_argument("--batch-max", type=int, default=None)
    p.add_argument("--retries", type=int, default=None)
    p.add_argument("--verify", default=None,
                   help="verify mode for the service's plans (e.g. 'on')")
    p.add_argument("--sched", type=int, choices=[0, 1], default=0,
                   help="A/B the task-graph scheduler (spfft_tpu.sched): 1 "
                   "dispatches mixed-geometry batches as one graph per "
                   "cycle; stamped into the report config either way")
    p.add_argument("--batch-fuse", type=int, choices=[0, 1], default=1,
                   help="A/B batch fusion (SPFFT_TPU_BATCH_FUSE): 1 runs a "
                   "coalesced batch as ONE stacked program dispatch per "
                   "direction, 0 keeps the split-phase per-request loop; "
                   "stamped into the report config either way")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--settle-s", type=float, default=30.0,
                   help="max wait for outstanding tickets after each step")
    p.add_argument("--hosts", type=int, default=0,
                   help="spawn N RPC worker hosts (spfft_tpu.hostmesh) and "
                   "drive the ClusterFront instead of an in-process "
                   "service; 0 = single-process. Host topology is stamped "
                   "in the report config and describe() either way")
    p.add_argument("--host-devices", type=int, default=1,
                   help="virtual CPU devices per spawned worker host")
    p.add_argument("--kill-host", type=int, default=None, metavar="K",
                   help="chaos: SIGKILL worker K mid-ramp (requires "
                   "--hosts); the row records completed_after_kill")
    p.add_argument("--kill-at", type=float, default=0.4,
                   help="when to kill, as a fraction of the first measured "
                   "step's offered window")
    p.add_argument("-o", "--output", default=None, help="write JSON report here")
    return p


def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


def run_step(service, *, key, rate, duration, tenants, trip, values, dims,
             transform_type, timeout_s, flops_per_transform, settle_s, rng,
             kill_fn=None, kill_at_s=None):
    """One measured open-loop step at ``rate`` requests/sec; returns the
    gate-compatible row. ``kill_fn`` (with ``kill_at_s`` seconds into the
    offered window) is the chaos hook: it fires once, mid-ramp, and the row
    additionally records when it fired and how many requests completed
    AFTER it — the surviving-hosts-keep-serving evidence."""
    from spfft_tpu.errors import (
        DeadlineExceededError,
        GenericError,
        ServiceOverloadError,
    )

    n_requests = max(1, int(round(rate * duration)))
    spacing = duration / n_requests
    tickets = []
    counts = {"offered": n_requests, "rejected": 0, "shed": 0,
              "deadline_miss": 0, "failed": 0}
    kill_mono = None
    t0 = time.perf_counter()
    for i in range(n_requests):
        target = t0 + i * spacing
        delay = target - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        if (
            kill_fn is not None and kill_mono is None
            and time.perf_counter() - t0 >= float(kill_at_s or 0.0)
        ):
            kill_mono = time.monotonic()
            kill_fn()
        tenant = f"tenant{i % tenants}"
        # per-request value perturbation: payloads differ per request the
        # way real traffic's do (coalescing must not depend on equal data)
        vals = values * (1.0 + 0.01 * rng.standard_normal())
        try:
            tickets.append(
                service.submit(
                    transform_type, dims, trip, vals, tenant=tenant,
                    timeout_s=timeout_s if timeout_s > 0 else None,
                )
            )
        except (ServiceOverloadError, DeadlineExceededError):
            counts["rejected"] += 1
        except GenericError:
            counts["failed"] += 1
    offered_wall = time.perf_counter() - t0

    latencies = []
    settle_deadline = time.time() + settle_s
    for t in tickets:
        try:
            t.result(timeout=max(0.05, settle_deadline - time.time()))
            latencies.append(t.latency_s())
        except DeadlineExceededError:
            counts["deadline_miss"] += 1
        except ServiceOverloadError:
            counts["shed"] += 1
        except (GenericError, TimeoutError):
            counts["failed"] += 1
    wall = time.perf_counter() - t0
    completed = len(latencies)
    completed_after_kill = None
    if kill_mono is not None:
        completed_after_kill = sum(
            1 for t in tickets
            if t.outcome == "completed"
            and t.finished_at is not None and t.finished_at > kill_mono
        )
    latencies.sort()
    p50 = _percentile(latencies, 0.50)
    p99 = _percentile(latencies, 0.99)
    noise = min(0.5, (p99 - p50) / p50) if p50 > 0 else 0.0
    # per-phase latency columns from the tickets' monotonic phase stamps
    # (admitted -> coalesced -> dispatched -> wire -> remote_execute ->
    # finalized): under overload the knee shows up as p99 growth in ONE
    # phase (queue wait = "coalesced"), not as an undifferentiated latency
    # blob — every resolved ticket contributes whatever stamps it reached
    phase_samples: dict = {}
    for t in tickets:
        for phase, seconds in t.phase_seconds().items():
            phase_samples.setdefault(phase, []).append(seconds)
    phases = {}
    for phase, vals in phase_samples.items():
        vals.sort()
        phases[phase] = {
            "n": len(vals),
            "p50_ms": round(_percentile(vals, 0.50) * 1e3, 3),
            "p99_ms": round(_percentile(vals, 0.99) * 1e3, 3),
        }
    row = {
        "key": key,
        "offered": n_requests,
        "offered_rate": round(n_requests / max(offered_wall, 1e-9), 3),
        "accepted": len(tickets),
        "completed": completed,
        "rejected": counts["rejected"],
        "shed": counts["shed"],
        "deadline_miss": counts["deadline_miss"],
        "failed": counts["failed"],
        "transforms_per_sec": round(completed / max(wall, 1e-9), 3),
        "p50_ms": round(p50 * 1e3, 3),
        "p99_ms": round(p99 * 1e3, 3),
        "phases": phases,
        "gflops": round(completed * flops_per_transform / max(wall, 1e-9) / 1e9, 6),
        "seconds_noise": round(noise, 4),
        "wall_seconds": round(wall, 4),
    }
    if kill_mono is not None:
        row["killed_at_s"] = round(float(kill_at_s or 0.0), 3)
        row["completed_after_kill"] = completed_after_kill
    return row


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import spfft_tpu as sp
    from spfft_tpu import TransformType, obs
    from spfft_tpu.obs import perf
    from spfft_tpu.serve import TransformService

    # the knob is read at dispatch time (spfft_tpu.ir.resolve_batch_fuse),
    # so setting the env here owns the whole run; write-only — reads go
    # through the typed registry
    os.environ["SPFFT_TPU_BATCH_FUSE"] = str(int(args.batch_fuse))
    dx, dy, dz = args.dims
    trip = sp.create_spherical_cutoff_triplets(dx, dy, dz, args.sparsity)
    rng = np.random.default_rng(args.seed)
    values = rng.standard_normal(len(trip)) + 1j * rng.standard_normal(len(trip))
    flops_per_transform = perf.dense_pair_flops((dx, dy, dz)) / 2.0
    dtype = "f64" if values.real.dtype == np.float64 else "f32"

    # argument validation BEFORE any worker is spawned: an early exit here
    # must never orphan child processes
    if args.kill_host is not None:
        if args.hosts <= 0:
            raise SystemExit("--kill-host requires --hosts N")
        if not 0 <= args.kill_host < args.hosts:
            raise SystemExit(
                f"--kill-host {args.kill_host} out of range for "
                f"--hosts {args.hosts}"
            )
    workers = []
    if args.hosts > 0:
        # multi-host mode: spawn the worker fleet, drive the ClusterFront —
        # same submit/ticket surface, admission now spans hosts
        from spfft_tpu import hostmesh
        from spfft_tpu.serve.cluster import ClusterFront

        workers = hostmesh.spawn_workers(
            args.hosts, devices_per_host=args.host_devices
        )
        try:
            service = ClusterFront(
                [w.address for w in workers],
                queue_capacity=args.queue_cap, batch_max=args.batch_max,
                retries=args.retries,
            )
        except BaseException:
            hostmesh.stop_workers(workers)
            raise
    else:
        service = TransformService(
            queue_capacity=args.queue_cap, batch_max=args.batch_max,
            retries=args.retries, verify=args.verify, sched=bool(args.sched),
        )
    kill_fn = None
    if args.kill_host is not None:
        kill_fn = workers[args.kill_host].kill
    rows = []
    try:
        # warmup outside the measured window: plan build, first compile, and
        # the clone pool (a batch_max burst forces the per-batch plan clones
        # to exist before any measured request can pay for them). Spread
        # across tenants and tolerate quota refusals: with a tiny queue the
        # admission rules apply to the warmup too, and a partially warmed
        # pool just grows lazily.
        from spfft_tpu.errors import ServiceOverloadError as _Overload

        warm = []
        for i in range(service.batch_max):
            try:
                warm.append(
                    service.submit(
                        TransformType.C2C, (dx, dy, dz), trip, values,
                        tenant=f"warmup{i % max(1, args.tenants)}",
                    )
                )
            except _Overload:
                break
        for tk in warm:
            tk.result(timeout=args.settle_s)
        # unmeasured preflight at the base rate: exercises the whole
        # dispatcher path (batch shapes, allocator, scheduler) under load
        # before the first recorded row, so row 1 measures steady state
        run_step(
            service, key="preflight", rate=args.rate,
            duration=min(1.0, args.duration), tenants=args.tenants,
            trip=trip, values=values, dims=(dx, dy, dz),
            transform_type=TransformType.C2C, timeout_s=0.0,
            flops_per_transform=flops_per_transform,
            settle_s=args.settle_s, rng=rng,
        )
        for step_i, mult in enumerate(args.ramp):
            rate = args.rate * mult
            family = "mhost" if args.hosts > 0 else "serve"
            hosts_token = f":h{args.hosts}" if args.hosts > 0 else ""
            key = (
                f"{family}:{dx}x{dy}x{dz}:s{int(round(args.sparsity * 100))}"
                f":c2c:{dtype}:t{args.tenants}{hosts_token}:x{mult:g}"
            )
            step_kill = kill_fn if (kill_fn is not None and step_i == 0) else None
            if step_kill is not None:
                key += ":chaos-kill"
            row = run_step(
                service, key=key, rate=rate, duration=args.duration,
                tenants=args.tenants, trip=trip, values=values,
                dims=(dx, dy, dz), transform_type=TransformType.C2C,
                timeout_s=args.timeout_s,
                flops_per_transform=flops_per_transform,
                settle_s=args.settle_s, rng=rng,
                kill_fn=step_kill,
                kill_at_s=args.kill_at * args.duration,
            )
            rows.append(row)
            queue_wait = row["phases"].get("coalesced")
            print(
                f"{row['key']}: offered {row['offered_rate']:.0f}/s -> "
                f"{row['transforms_per_sec']:.0f} done/s "
                f"(p50 {row['p50_ms']:.1f} ms, p99 {row['p99_ms']:.1f} ms, "
                + (
                    f"queue-wait p99 {queue_wait['p99_ms']:.1f} ms, "
                    if queue_wait else ""
                )
                + f"rejected {row['rejected']}, shed {row['shed']}, "
                f"deadline {row['deadline_miss']}, failed {row['failed']})"
            )
    finally:
        described = service.describe()
        topology = [w.describe() for w in workers] or None
        service.close()
        if workers:
            hostmesh.stop_workers(workers)

    doc = {
        "schema": LOADGEN_SCHEMA,
        "run_unix": time.time(),
        "config": {
            "dims": [dx, dy, dz], "sparsity": args.sparsity,
            "tenants": args.tenants, "base_rate": args.rate,
            "ramp": list(args.ramp), "duration_s": args.duration,
            "timeout_s": args.timeout_s, "num_values": int(len(trip)),
            "flops_per_transform": flops_per_transform, "dtype": dtype,
            "seed": args.seed, "sched": bool(args.sched),
            "batch_fuse": bool(args.batch_fuse),
            # host topology: single-process (hosts=0) vs multi-host captures
            # are distinguishable from the committed JSON alone
            "hosts": int(args.hosts),
            "host_devices": int(args.host_devices) if args.hosts else None,
            "topology": topology,
            "kill_host": args.kill_host,
        },
        "rows": rows,
        "service": described,
        "metrics": obs.snapshot(),
    }
    if args.output:
        Path(args.output).write_text(json.dumps(doc, indent=1, sort_keys=True))
        print(f"wrote {args.output}")
    else:
        json.dump(doc, sys.stdout, indent=1, sort_keys=True)
        print()
    return 0


if __name__ == "__main__":
    sys.exit(main())
