"""Generate the API reference (docs/api/*.md) from live docstrings + headers.

The analogue of the reference's Sphinx/Doxygen pages (reference:
docs/source/{grid,transform,multi_transform,types,errors_c,...}.rst — 18
pages): the Python pages are introspected from the installed package so they
cannot drift from the code, the C page is rendered from the shipped headers,
and the Fortran page from the bind(C) module. ``tests/test_api_docs.py``
regenerates into a scratch dir and diffs against the committed pages, so a
stale reference fails CI.

Usage: python programs/gen_api_docs.py [outdir]   (default docs/api)
"""
from __future__ import annotations

import inspect
import sys
import textwrap
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))
sys.path.insert(0, str(ROOT / "programs"))

from api_surface import (  # noqa: E402
    C_HEADER_NAMES,
    F90_PATH,
    c_prototypes,
    fortran_functions,
)


def doc(obj) -> str:
    d = inspect.getdoc(obj)
    return d.strip() if d else ""


def sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def class_page(title: str, intro: str, classes, functions=()) -> str:
    out = [f"# {title}", "", intro.strip(), ""]
    for cls in classes:
        out += [f"## class `{cls.__name__}`", "", doc(cls), ""]
        init = cls.__dict__.get("__init__")
        if init is not None:
            out += [f"### `{cls.__name__}{sig(init)}`", ""]
            init_doc = doc(init)
            if init_doc and not init_doc.startswith("Initialize self"):
                out += [init_doc, ""]
        members = []
        for name, member in sorted(vars(cls).items()):
            if name.startswith("_"):
                continue
            members.append((name, member))
        props = [(n, m) for n, m in members if isinstance(m, property)]
        methods = [(n, m) for n, m in members if inspect.isfunction(m)]
        if props:
            out += ["### Properties", ""]
            for name, p in props:
                line = f"- **`{name}`**"
                if doc(p):
                    line += f" — {doc(p).splitlines()[0]}"
                out.append(line)
            out.append("")
        if methods:
            out += ["### Methods", ""]
            for name, m in methods:
                out += [f"#### `{name}{sig(m)}`", ""]
                if doc(m):
                    out += [doc(m), ""]
    for fn in functions:
        out += [f"## `{fn.__name__}{sig(fn)}`", ""]
        if doc(fn):
            out += [doc(fn), ""]
    return "\n".join(out).rstrip() + "\n"


def enum_page() -> str:
    import spfft_tpu as sp

    enums = [
        sp.TransformType,
        sp.ProcessingUnit,
        sp.IndexFormat,
        sp.ScalingType,
        sp.ExecType,
        sp.ExchangeType,
    ]
    out = [
        "# Types",
        "",
        "Enum surface, ABI-compatible with the reference C enums"
        " (`SPFFT_*` integer aliases are exported at package level"
        " for ported code).",
        "",
    ]
    for e in enums:
        out += [f"## `{e.__name__}`", "", doc(e), "", "| name | value |", "|---|---|"]
        for member in e:
            out.append(f"| `{member.name}` | {int(member.value)} |")
        out.append("")
    return "\n".join(out).rstrip() + "\n"


def errors_page() -> str:
    import spfft_tpu.errors as err

    out = [
        "# Errors",
        "",
        doc(err) or "Exception hierarchy and C error codes.",
        "",
        "## Error codes (`ErrorCode`)",
        "",
        "| name | value |",
        "|---|---|",
    ]
    for member in err.ErrorCode:
        out.append(f"| `{member.name}` | {int(member.value)} |")
    out += ["", "## Exceptions", ""]
    for name, cls in sorted(vars(err).items()):
        if inspect.isclass(cls) and issubclass(cls, Exception):
            bases = ", ".join(b.__name__ for b in cls.__bases__)
            first = doc(cls).splitlines()[0] if doc(cls) else ""
            out.append(f"- **`{name}`**({bases}) — {first}")
    return "\n".join(out).rstrip() + "\n"


def c_api_page() -> str:
    headers = ["errors.h", "types.h", "grid.h", "transform.h", "multi_transform.h"]
    out = [
        "# C API",
        "",
        "Opaque-handle C interface of `libspfft_tpu` (link via"
        " `find_package(SpFFTTPU)` or `pkg-config spfft_tpu`; see"
        " [installation](installation.md)). Every function returns"
        " `SpfftError`. The float (`spfft_float_*`) entry points mirror the"
        " double ones at single precision.",
        "",
    ]
    for header in headers:
        path = ROOT / "native" / "include" / "spfft" / header
        protos = c_prototypes(path)
        out += [f"## `<spfft/{header}>`", ""]
        if not protos:
            out += [
                "Enum/typedef surface only (values tabulated in"
                " [types](types.md) and [errors](errors.md)).",
                "",
            ]
            continue
        for name, args in protos:
            out.append(f"- `SpfftError {name}({', '.join(args)})`")
        out.append("")
    return "\n".join(out).rstrip() + "\n"


def fortran_page() -> str:
    names = list(fortran_functions(F90_PATH))
    out = [
        "# Fortran module",
        "",
        "`module spfft` (`native/include/spfft/spfft.f90`): `bind(C)`"
        " interfaces over the whole C API plus the enum constants, compiled"
        " into the application like the reference's module. Surface is"
        " machine-checked against the C headers by"
        " `tests/test_fortran_surface.py`.",
        "",
        f"{len(names)} bound functions:",
        "",
    ]
    out += [f"- `{n}`" for n in names]
    return "\n".join(out).rstrip() + "\n"


def examples_page() -> str:
    out = [
        "# Examples",
        "",
        "Runnable sources in `examples/` (the reference ships the same set:"
        " C, C++, Fortran and a mini application).",
        "",
    ]
    lang = {".py": "python", ".c": "c", ".cpp": "cpp", ".f90": "fortran"}
    paths = [
        p
        for p in sorted((ROOT / "examples").iterdir())
        if p.is_file() and p.suffix in lang
    ]
    for path in paths:
        out += [
            f"## `{path.name}`",
            "",
            f"```{lang.get(path.suffix, '')}",
            path.read_text().rstrip(),
            "```",
            "",
        ]
    return "\n".join(out).rstrip() + "\n"


def installation_page() -> str:
    return textwrap.dedent(
        """\
        # Installation

        ## Python package

        The package is pure Python over JAX; put the repository root on
        `PYTHONPATH` (or `pip install -e .`-style vendoring into your tree)
        and `import spfft_tpu`. Dependencies: `jax`, `numpy`, `ml_dtypes`
        (all standard in a JAX TPU environment).

        ## Native library

        ```sh
        cmake -S native -B native/build -DCMAKE_BUILD_TYPE=Release \\
              -DCMAKE_INSTALL_PREFIX=/opt/spfft_tpu
        cmake --build native/build
        cmake --install native/build
        ```

        Installs `libspfft_tpu` (embedded-CPython runtime over the same
        compute core), the `spfft/*.h` headers, the Fortran module source,
        `SpFFTTPUConfig.cmake` (consume with
        `find_package(SpFFTTPU); target_link_libraries(app SpFFTTPU::spfft_tpu)`)
        and `spfft_tpu.pc` for pkg-config builds. The embedded interpreter
        needs `spfft_tpu` importable at runtime (`PYTHONPATH`).

        ## Verifying

        `python -m pytest tests/ -x -q` runs the full suite on a virtual
        8-device CPU mesh; `python bench.py` prints the headline benchmark on
        the attached accelerator.
        """
    )


def ir_page() -> str:
    """The stage-graph IR page: the `spfft_tpu.ir` surface (graphs, the
    fusion pass, the staged reference executor, the engine runtime)."""
    from spfft_tpu import ir

    return class_page(
        "Stage-graph IR (`spfft_tpu.ir`)",
        doc(ir),
        [ir.StageGraph, ir.EdgeMeta, ir.Node, ir.StagedProgram, ir.EngineIr],
        [
            ir.compose,
            ir.resolve_fuse,
            ir.lower_engine,
            ir.init_engine_ir,
        ],
    )


def index_page() -> str:
    import spfft_tpu as sp

    return textwrap.dedent(
        f"""\
        # spfft_tpu API reference (v{sp.__version__})

        {doc(sp).splitlines()[0]}

        Generated by `programs/gen_api_docs.py` from the live package —
        regenerate after API changes (`tests/test_api_docs.py` enforces it).

        - [Installation](installation.md)
        - [Types and enums](types.md)
        - [Errors](errors.md)
        - [Grid](grid.md)
        - [Transform](transform.md)
        - [Distributed transform](distributed.md)
        - [Multi-transforms](multi_transform.md)
        - [Index helpers and mesh utilities](utilities.md)
        - [Observability: plan cards, metrics, execution trace](obs.md)
        - [Fleet metrics and cross-host trace propagation](fleet.md)
        - [Performance reports and the scaling bench](perf.md)
        - [Autotuning and wisdom](tuning.md)
        - [Fault injection, guard mode and degradation](faults.md)
        - [Self-verification (ABFT), recovery and the circuit breaker](verify.md)
        - [Serving: admission, coalesced batching, load shedding](serve.md)
        - [Multi-host serving: bootstrap, RPC front, host-loss ladder](hostmesh.md)
        - [Task-graph scheduling: placement, overlap, completion order](sched.md)
        - [Stage-graph IR and per-direction fusion](ir.md)
        - [Static analysis: the checker catalog and the baselined gate](analysis.md)
        - [C API](c_api.md)
        - [Fortran module](fortran.md)
        - [Examples](examples.md)

        Architecture and semantics prose lives in [docs/details.md]
        (../details.md); porting notes from the reference library in
        [docs/MIGRATION.md](../MIGRATION.md).
        """
    )


def obs_page() -> str:
    """The observability page: the `spfft_tpu.obs` surface (plan cards +
    run metrics) and the `spfft_tpu.obs.trace` flight recorder, one page —
    they share the run-ID join key."""
    from spfft_tpu import obs
    from spfft_tpu.obs import trace

    metrics = class_page(
        "Observability",
        doc(obs),
        [],
        [
            obs.counter,
            obs.gauge,
            obs.histogram,
            obs.phase_timer,
            obs.enable,
            obs.disable,
            obs.is_enabled,
            obs.clear,
            obs.snapshot,
            obs.validate_snapshot,
            obs.prometheus_text,
            obs.plan_card,
            obs.validate_plan_card,
            obs.validate_report,
        ],
    )
    tracing = class_page(
        "Execution trace (`spfft_tpu.obs.trace`)",
        doc(trace),
        [trace.TraceRecorder],
        [
            trace.enable,
            trace.disable,
            trace.enabled,
            trace.clear,
            trace.new_run_id,
            trace.current_run_id,
            trace.event,
            trace.span,
            trace.operation,
            trace.snapshot,
            trace.validate_trace,
            trace.chrome_trace,
            trace.dump,
            trace.suppressed_dumps,
        ],
    )
    return metrics + "\n" + tracing


def perf_page() -> str:
    """The performance page: the `spfft_tpu.obs.perf` surface (measurement
    discipline, stage attribution, report/scaling-doc schemas)."""
    from spfft_tpu.obs import perf

    return class_page(
        "Performance reports (`spfft_tpu.obs.perf`)",
        doc(perf),
        [],
        [
            perf.measure_pair_seconds,
            perf.perf_report,
            perf.stage_model,
            perf.fft_pass_flops,
            perf.dense_pair_flops,
            perf.flop_per_byte,
            perf.validate_perf_report,
            perf.validate_scaling_doc,
        ],
    )


def fleet_page() -> str:
    """The fleet observability page: `spfft_tpu.obs.fleet` (scrape + merge
    + schema pin + exposition) and the cross-host trace propagation trio
    (`trace.segment` / `validate_segment` / `splice`) — one page, they are
    the two halves of the layer-6 story."""
    from spfft_tpu.obs import fleet, trace

    merged = class_page(
        "Fleet metrics (`spfft_tpu.obs.fleet`)",
        doc(fleet),
        [],
        [
            fleet.fleet_snapshot,
            fleet.merge_snapshots,
            fleet.validate_fleet,
            fleet.fleet_prometheus_text,
            fleet.parse_series_key,
            fleet.host_series_key,
            fleet.resolve_scrape_s,
        ],
    )
    propagation = class_page(
        "Cross-host trace propagation (`spfft_tpu.obs.trace`)",
        "Compact schema-pinned trace segments carried on RPC replies: the "
        "worker cuts its spans under the caller's run ID "
        "(`trace.segment`), the front validates and splices them into its "
        "own flight recorder tagged `host=` (`trace.splice`), so one "
        "`trace.snapshot()` shows both sides of a dispatch under the "
        "submitting request's run ID.",
        [],
        [
            trace.segment,
            trace.validate_segment,
            trace.splice,
        ],
    )
    return merged + "\n" + propagation


def verify_page() -> str:
    """The verification page: the `spfft_tpu.verify` surface (ABFT checks,
    the recovery supervisor, the engine circuit breaker)."""
    from spfft_tpu import verify
    from spfft_tpu.verify import breaker

    main = class_page(
        "Verification",
        doc(verify),
        [verify.Supervisor],
        [
            verify.resolve_mode,
            verify.resolve_rtol,
            verify.resolve_retries,
            verify.resolve_backoff_s,
            verify.jitter_rng,
            verify.applicable_checks,
            verify.run_checks,
        ],
    )
    brk = class_page(
        "Engine circuit breaker (`spfft_tpu.verify.breaker`)",
        doc(breaker),
        [],
        [
            breaker.allow,
            breaker.record_success,
            breaker.record_failure,
            breaker.describe,
            breaker.snapshot,
            breaker.reset,
            breaker.threshold,
            breaker.cooldown_s,
        ],
    )
    return main + "\n" + brk


def serve_page() -> str:
    """The serving page: the `spfft_tpu.serve` surface (admission queue,
    plan cache + coalescing, the overload-safe service)."""
    from spfft_tpu import serve

    return class_page(
        "Serving (`spfft_tpu.serve`)",
        doc(serve),
        [serve.TransformService, serve.Ticket, serve.AdmissionQueue,
         serve.PlanCache],
        [
            serve.canonical_triplets,
            serve.wrap_triplets,
            serve.resolve_on_breaker,
            serve.as_typed,
        ],
    )


def hostmesh_page() -> str:
    """The multi-host page: the `spfft_tpu.hostmesh` bootstrap plus the
    cross-host serving surface (`serve.rpc` / `serve.cluster`)."""
    from spfft_tpu import hostmesh, serve
    from spfft_tpu.serve import rpc

    boot = class_page(
        "Multi-host bootstrap (`spfft_tpu.hostmesh`)",
        doc(hostmesh),
        [hostmesh.WorkerHost],
        [
            hostmesh.boot,
            hostmesh.spawn_workers,
            hostmesh.stop_workers,
            hostmesh.child_env,
            hostmesh.warm_start,
            hostmesh.free_port,
        ],
    )
    front = class_page(
        "Cross-host serving (`spfft_tpu.serve.cluster` / `serve.rpc`)",
        doc(serve.cluster),
        [serve.ClusterFront, serve.HeartbeatMonitor, serve.HostHandle,
         serve.RemotePlan, serve.RpcServer, serve.RpcClient],
        [
            rpc.send_msg,
            rpc.recv_msg,
            rpc.encode_array,
            rpc.decode_value,
            rpc.resolve_timeout_s,
        ],
    )
    return boot + "\n" + front


def sched_page() -> str:
    """The scheduling page: the `spfft_tpu.sched` surface (task graphs,
    the tuned placement pass, the completion-order executor)."""
    from spfft_tpu import sched

    return class_page(
        "Task-graph scheduling (`spfft_tpu.sched`)",
        doc(sched),
        [sched.TaskGraph, sched.Task, sched.PlanPool, sched.GraphReport],
        [
            sched.run_graph,
            sched.run_tasks,
            sched.resolve_inflight,
            sched.resolve_width,
            sched.workload_key,
            sched.build_plan,
        ],
    )


def analysis_page() -> str:
    """The static-analysis page: the checker catalog rendered from the
    live registry (code/severity/doc per checker), plus the gate and
    baseline workflow."""
    import spfft_tpu.analysis as analysis

    out = [
        "# Static analysis (`spfft_tpu.analysis`)",
        "",
        doc(analysis),
        "",
        "## Checker catalog",
        "",
        "| Code | Checker | Severity | What it enforces |",
        "|---|---|---|---|",
    ]
    for entry in analysis.CHECKERS.values():
        escaped = entry.doc.replace("|", "\\|")
        out.append(
            f"| `{entry.code}` | `{entry.name}` | {entry.severity} | "
            f"{escaped} |"
        )
    out += [
        "",
        "## Running the gate",
        "",
        "```",
        "python programs/analyze.py                # full gate (exit 3 on new findings)",
        "python programs/analyze.py --json report.json",
        "python programs/analyze.py --only SA011   # one checker",
        "python programs/analyze.py --write-baseline",
        "python programs/analyze.py --list-noqa    # suppression audit (orphans exit 3)",
        "python programs/analyze.py --jobs 1       # serial reference run",
        "python programs/analyze.py --lockdep-check report.json",
        "```",
        "",
        "Findings are suppressed per line with `# noqa: <CODE>`; accepted "
        "pre-existing findings live in the committed `analysis_baseline.json` "
        "(keyed `CODE:file:message`, line-number-free). New findings AND "
        "stale baseline entries (a fixed finding must leave the baseline) "
        "exit 3 — `./ci.sh analyze` proves the trip on doctored fixtures, "
        "one per deep checker (lock-order cycle, use-after-donate, batched "
        "use-after-consume, rogue metric, leaked thread, untested fault "
        "site, sleep-in-span). `--list-noqa` audits every `# noqa: SA*` "
        "suppression and exits 3 on ORPHANED ones (the code no longer "
        "fires there). Checkers run on a thread pool (`--jobs`), findings "
        "identical to the serial reference. `programs/lint.py` is a thin "
        "shim running the ported checkers SA001-SA009.",
        "",
        "## Runtime lockdep (`spfft_tpu.analysis.lockdep`)",
        "",
        doc(analysis.lockdep),
        "",
        "See docs/details.md \"Static analysis & runtime lockdep\" for the "
        "two-layer story, the baseline workflow, and how to add a checker.",
        "",
    ]
    return "\n".join(out)


KNOB_TABLE_BEGIN = "<!-- knob-table:begin (generated from spfft_tpu.knobs by programs/gen_api_docs.py — edit docs in the registry, not here) -->"
KNOB_TABLE_END = "<!-- knob-table:end -->"


def knob_table() -> str:
    """The docs/details.md knob table, rendered from the registry (the
    single holder of name/kind/default/doc — SA003 keeps the two in sync)."""
    from spfft_tpu import knobs

    rows = [
        "| Knob | Default | Effect |",
        "|---|---|---|",
    ]
    # registration order, not sorted: the registry groups knobs by
    # subsystem (engine, tuning, obs, faults, verify, serve) and the table
    # keeps that narrative
    for knob in knobs.REGISTRY.values():
        row = knob.describe()
        if row["internal"]:
            continue
        if row["doc_default"] is not None:
            default = row["doc_default"]
        elif row["default"] is None:
            default = "—"
        else:
            v = row["default"]
            if isinstance(v, bool):
                v = int(v)
            elif isinstance(v, float) and v == int(v):
                v = int(v)
            default = f"`{v}`"
        escaped = row["doc"].replace("|", "\\|")
        rows.append(f"| `{row['name']}` | {default} | {escaped} |")
    return "\n".join(rows)


def rewrite_knob_table(details_path: Path) -> None:
    """Replace the marked knob-table block in docs/details.md in place."""
    text = details_path.read_text()
    begin = text.index(KNOB_TABLE_BEGIN)
    end = text.index(KNOB_TABLE_END)
    text = (
        text[: begin + len(KNOB_TABLE_BEGIN)]
        + "\n"
        + knob_table()
        + "\n"
        + text[end:]
    )
    details_path.write_text(text)
    print(f"rewrote knob table in {details_path}")


METRIC_TABLE_BEGIN = "<!-- metric-table:begin (generated from spfft_tpu.obs.metrics by programs/gen_api_docs.py — edit docs in the vocabulary, not here) -->"
METRIC_TABLE_END = "<!-- metric-table:end -->"


def metric_table() -> str:
    """The docs/details.md metric table, rendered from the canonical
    run-metrics vocabulary (``spfft_tpu/obs/metrics.py`` — SA016 keeps the
    two in sync both ways, the knob-table contract)."""
    from spfft_tpu.obs import metrics

    rows = [
        "| Metric | Kind | Labels | What it records |",
        "|---|---|---|---|",
    ]
    # declaration order, not sorted: the vocabulary groups instruments by
    # subsystem and the table keeps that narrative
    for row in metrics.describe():
        labels = ", ".join(f"`{k}`" for k in row["labels"]) or "—"
        escaped = row["doc"].replace("|", "\\|")
        rows.append(
            f"| `{row['name']}` | {row['kind']} | {labels} | {escaped} |"
        )
    return "\n".join(rows)


def rewrite_metric_table(details_path: Path) -> None:
    """Replace the marked metric-table block in docs/details.md in place."""
    text = details_path.read_text()
    begin = text.index(METRIC_TABLE_BEGIN)
    end = text.index(METRIC_TABLE_END)
    text = (
        text[: begin + len(METRIC_TABLE_BEGIN)]
        + "\n"
        + metric_table()
        + "\n"
        + text[end:]
    )
    details_path.write_text(text)
    print(f"rewrote metric table in {details_path}")


def generate(outdir: Path) -> None:
    import spfft_tpu as sp
    from spfft_tpu import faults, timing, tuning
    from spfft_tpu.parallel import mesh

    outdir.mkdir(parents=True, exist_ok=True)
    pages = {
        "index.md": index_page(),
        "installation.md": installation_page(),
        "types.md": enum_page(),
        "errors.md": errors_page(),
        "grid.md": class_page(
            "Grid",
            "Transform capacity holder (local and mesh-distributed ctors).",
            [sp.Grid],
        ),
        "transform.md": class_page(
            "Transform",
            "Local sparse 3D FFT plans (`TransformFloat` is the single-"
            "precision alias; precision is otherwise a `dtype` argument).",
            [sp.Transform],
        ),
        "distributed.md": class_page(
            "DistributedTransform",
            "Mesh-sharded transforms (1-D slab and 2-D pencil decompositions).",
            [sp.DistributedTransform],
        ),
        "multi_transform.md": class_page(
            "Multi-transforms",
            "Batched pipelined execution of independent transforms "
            "(the split-phase dispatch/finalize halves are public for batch "
            "owners like the serving layer).",
            [],
            [
                sp.multi_transform_backward,
                sp.multi_transform_forward,
                sp.multi_transform.dispatch_backward,
                sp.multi_transform.finalize_backward,
                sp.multi_transform.dispatch_forward,
                sp.multi_transform.finalize_forward,
            ],
        ),
        "utilities.md": class_page(
            "Utilities",
            "Index generation, stick distribution, mesh construction, "
            "multi-host init, and the timing subsystem "
            "(`spfft_tpu.timing` mirrors the reference's rt_graph).",
            [],
            [
                sp.create_spherical_cutoff_triplets,
                sp.spherical_radius_for_fraction,
                sp.distribute_triplets,
                sp.make_fft_mesh,
                sp.make_fft_mesh2,
                sp.init_distributed,
                mesh.ensure_virtual_devices,
                timing.enable,
                timing.scoped,
            ],
        ),
        "obs.md": obs_page(),
        "fleet.md": fleet_page(),
        "perf.md": perf_page(),
        "tuning.md": class_page(
            "Tuning",
            doc(tuning),
            [tuning.WisdomStore],
            [
                tuning.tuned_exchange,
                tuning.tuned_local,
                tuning.exchange_candidates,
                tuning.local_candidates,
                tuning.sched_candidates,
                tuning.wisdom_state,
                tuning.active_store,
                tuning.best_measured_ms,
                tuning.merge_entries,
                tuning.clear_memory,
                tuning.trial_deadline_s,
            ],
        ),
        "faults.md": class_page(
            "Faults",
            doc(faults),
            [],
            [
                faults.arm,
                faults.disarm,
                faults.armed,
                faults.inject,
                faults.reseed,
                faults.site,
                faults.parse_spec,
                faults.guard_enabled,
                faults.check_array,
                faults.check_device,
                faults.execution_error,
                faults.collecting,
                faults.record_degradation,
                faults.engine_fallback,
                faults.summarize,
                faults.typed_execution,
                faults.backoff_s,
            ],
        ),
        "verify.md": verify_page(),
        "serve.md": serve_page(),
        "hostmesh.md": hostmesh_page(),
        "sched.md": sched_page(),
        "ir.md": ir_page(),
        "analysis.md": analysis_page(),
        "c_api.md": c_api_page(),
        "fortran.md": fortran_page(),
        "examples.md": examples_page(),
    }
    for name, content in pages.items():
        (outdir / name).write_text(content)
    print(f"wrote {len(pages)} pages to {outdir}")


if __name__ == "__main__":
    if len(sys.argv) > 1:
        # scratch regeneration (tests/test_api_docs.py): the committed
        # details.md is left alone
        generate(Path(sys.argv[1]))
    else:
        generate(ROOT / "docs" / "api")
        rewrite_knob_table(ROOT / "docs" / "details.md")
        rewrite_metric_table(ROOT / "docs" / "details.md")
