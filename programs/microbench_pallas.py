"""Microbench: Pallas fused complex matmul vs 4-einsum, on real plan shapes.

Builds the 256^3 spherical-cutoff plan, extracts the actual MXU stage shapes,
and times both paths on the attached device. Decides whether wiring
ops/pallas_fft.complex_matmul_fused into the engine pays.
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

import numpy as np

import jax
import jax.numpy as jnp

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import spfft_tpu as sp
from spfft_tpu.execution_mxu import MxuLocalExecution
from spfft_tpu.ops import fft as offt
from spfft_tpu.ops import pallas_fft
from spfft_tpu.parameters import make_local_parameters
from spfft_tpu.types import TransformType


def timeit(fn, args, reps=200):
    """Time `reps` dependent iterations inside ONE compiled scan (excludes the
    per-dispatch tunnel latency, same methodology as programs/benchmark.py)."""

    @jax.jit
    def loop(a, b):
        def body(carry, _):
            r, i = fn(carry[0], carry[1])
            return (r, i), ()

        (r, i), _ = jax.lax.scan(body, (a, b), None, length=reps)
        return r.ravel()[0] + i.ravel()[0]

    # Fence by fetching the scalar: block_until_ready does NOT wait for
    # execution on the tunneled axon TPU (see benchmark.py's fence()).
    float(loop(*args))
    t0 = time.perf_counter()
    out = float(loop(*args))
    del out
    return (time.perf_counter() - t0) / reps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=256)
    ap.add_argument("--sparsity", type=float, default=0.15)
    args = ap.parse_args()

    d = args.dim
    # nnz fraction -> ball radius fraction (matches benchmark.py's spherical model)
    radius = float((6.0 * args.sparsity / np.pi) ** (1.0 / 3.0))
    trip = sp.create_spherical_cutoff_triplets(d, d, d, radius)
    params = make_local_parameters(TransformType.C2C, d, d, d, trip)
    ex = MxuLocalExecution(params, real_dtype=np.float32)
    S, Z, Y, A = params.num_sticks, params.dim_z, params.dim_y, ex._num_x_active
    print(f"plan: S={S} Z={Z} Y={Y} A={A}")

    rng = np.random.default_rng(0)
    prec = jax.lax.Precision.HIGHEST

    # ---- z stage: (S, Z) @ (Z, Z), pure 2D ----
    # pad S to sublane multiple for the pallas variant
    Sp = -(-S // 8) * 8
    xr = jnp.asarray(rng.standard_normal((Sp, Z)).astype(np.float32))
    xi = jnp.asarray(rng.standard_normal((Sp, Z)).astype(np.float32))
    wr, wi = (jnp.asarray(w) for w in ex._wz_b)

    ein = jax.jit(
        lambda a, b: offt.complex_matmul(a, b, wr, wi, "sz,zk->sk", prec)
    )
    t_ein = timeit(ein, (xr, xi))

    if pallas_fft.supports(Sp, Z, Z, np.float32):
        pal = jax.jit(
            lambda a, b: pallas_fft.complex_matmul_fused(a, b, wr, wi)
        )
        t_pal = timeit(pal, (xr, xi))
        # check numerics
        er, ei = jax.device_get(ein(xr, xi))
        pr, pi = jax.device_get(pal(xr, xi))
        err = max(
            float(np.abs(er - pr).max()), float(np.abs(ei - pi).max())
        )
    else:
        t_pal, err = float("nan"), float("nan")
    print(
        f"z-stage  ({Sp}x{Z} @ {Z}x{Z}):  einsum {t_ein*1e3:8.3f} ms   "
        f"pallas {t_pal*1e3:8.3f} ms   maxerr {err:.2e}"
    )

    # ---- y stage as W@X 2D: (Y,Y) @ (Y, A*Z) via x-transposed form ----
    # einsum native 3D form
    g_r = jnp.asarray(rng.standard_normal((Y, A, Z)).astype(np.float32))
    g_i = jnp.asarray(rng.standard_normal((Y, A, Z)).astype(np.float32))
    wyr, wyi = (jnp.asarray(w) for w in ex._wy_b)
    ein_y = jax.jit(
        lambda a, b: offt.complex_matmul(a, b, wyr, wyi, "yxz,yk->kxz", prec)
    )
    t_ein_y = timeit(ein_y, (g_r, g_i))

    # pallas: reshape to (Y, A*Z), want W^T X -> compute (X^T W)^T without
    # materialized transpose? Here just test X-major form: (A*Z, Y) @ (Y, K).
    h_r = jnp.asarray(np.ascontiguousarray(
        np.moveaxis(np.asarray(g_r), 0, -1).reshape(A * Z, Y)))
    h_i = jnp.asarray(np.ascontiguousarray(
        np.moveaxis(np.asarray(g_i), 0, -1).reshape(A * Z, Y)))
    if pallas_fft.supports(A * Z, Y, Y, np.float32):
        pal_y = jax.jit(
            lambda a, b: pallas_fft.complex_matmul_fused(a, b, wyr, wyi)
        )
        t_pal_y = timeit(pal_y, (h_r, h_i))
    else:
        t_pal_y = float("nan")
    print(
        f"y-stage  3D einsum {t_ein_y*1e3:8.3f} ms   "
        f"pallas-2D ({A*Z}x{Y} @ {Y}x{Y}) {t_pal_y*1e3:8.3f} ms"
    )

    # ---- x stage einsum for context ----
    wxr, wxi = (jnp.asarray(w) for w in ex._wx_b)
    def ein_x(a, b):
        r, i = offt.complex_matmul(a, b, wxr, wxi, "kxz,xl->klz", prec)
        return r[:, :A, :], i[:, :A, :]  # slice back so the scan chains

    t_ein_x = timeit(ein_x, (g_r, g_i))
    print(f"x-stage  3D einsum {t_ein_x*1e3:8.3f} ms")


if __name__ == "__main__":
    main()
