"""Pallas-DMA row-copy A/B, attempt 3: scalar prefetch, halved calls.

Attempt 1: full-R scalar prefetch exceeds the 1 MB SMEM (1.44 MB of idx).
Attempt 2: blocked SMEM in_specs hit rank-1/rank-2 tiling constraints.
This version keeps PrefetchScalarGridSpec but runs TWO half-R calls (720 KB
of prefetched idx each) and concatenates — one extra dispatch, bounded SMEM.

Appends to bench_results/round5_pallas_dma.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_pallas_dma.json"
)

LANE = 128


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "microbench_pallas_dma3", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    rng = np.random.default_rng(0)
    M = 735_000
    R = 360_448
    H = R // 2
    idx = np.sort(rng.choice(M, size=R, replace=False)).astype(np.int32)
    src = jnp.asarray(rng.standard_normal((M, LANE)).astype(np.float32))
    idx_a = jnp.asarray(idx[:H])
    idx_b = jnp.asarray(idx[H:])

    REPS = 32

    def timed(name, fn, extra=None):
        @jax.jit
        def loop(s):
            def body(carry, _):
                out = fn(carry)
                return carry.at[:LANE, :].set(out[:LANE, :]), ()

            final, _ = jax.lax.scan(body, s, None, length=REPS)
            return final.ravel()[0]

        try:
            float(jax.device_get(loop(src)))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = loop(src)
                float(jax.device_get(out))
                best = min(best, (time.perf_counter() - t0) / REPS)
            row = {"name": name, "ms": round(best * 1e3, 3),
                   "ns_per_row": round(best / R * 1e9, 2)}
            if extra:
                row.update(extra)
            record(row)
            return best
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"[:300]})
            return None

    def make_half_kernel(T):
        def kernel(idx_ref, src_ref, out_ref, sems):
            i = pl.program_id(0)
            for j in range(T):
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[i * T + j]], out_ref.at[j], sems.at[j]
                ).start()
            for j in range(T):
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[i * T + j]], out_ref.at[j], sems.at[j]
                ).wait()

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(H // T,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(
                (T, LANE), lambda i, idx_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.SemaphoreType.DMA((T,))],
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((H, LANE), jnp.float32),
            grid_spec=grid_spec,
        )

    # correctness check once at T=64
    try:
        k = make_half_kernel(64)
        out = jnp.concatenate([k(idx_a, src), k(idx_b, src)])
        ref = np.asarray(src)[idx]
        err = float(np.abs(np.asarray(out) - ref).max())
        record({"name": "pallas_half_correctness", "max_err": err})
        assert err == 0.0
    except Exception as e:
        record({"name": "pallas_half_correctness",
                "error": f"{type(e).__name__}: {e}"[:300]})

    for T in (32, 64, 128, 512):
        try:
            k = make_half_kernel(T)
            timed(
                f"pallas_half_T{T}",
                lambda s, k=k: jnp.concatenate([k(idx_a, s), k(idx_b, s)]),
                extra={"T": T},
            )
        except Exception as e:
            record({"name": f"pallas_half_T{T}",
                    "error": f"{type(e).__name__}: {e}"[:300]})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
