"""Shared parsers for the native API surface (C headers + Fortran module).

Single source for everything that pattern-matches the shipped interface files:
the surface-verification tests (tests/test_fortran_surface.py) and the API
reference generator (programs/gen_api_docs.py) must see the SAME prototype
set, so they parse through these helpers rather than private copies.
"""
from __future__ import annotations

import re
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
F90_PATH = ROOT / "native" / "include" / "spfft" / "spfft.f90"
C_HEADER_NAMES = ("grid.h", "transform.h", "multi_transform.h")
C_HEADER_PATHS = tuple(
    ROOT / "native" / "include" / "spfft" / name for name in C_HEADER_NAMES
)


def join_continuations(text: str) -> str:
    """Fortran free-form: a trailing '&' continues the statement."""
    return re.sub(r"&\s*\n\s*", " ", text)


def strip_c_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.DOTALL)
    text = re.sub(r"//[^\n]*", " ", text)
    return text


def fortran_functions(path: Path = F90_PATH) -> dict:
    """{lowercased name: arg count} for every bind(C) function interface."""
    text = join_continuations(path.read_text())
    out = {}
    for m in re.finditer(
        r"function\s+(spfft_\w+)\s*\(([^)]*)\)\s*bind\s*\(\s*C", text, re.IGNORECASE
    ):
        args = [a.strip() for a in m.group(2).split(",") if a.strip()]
        out[m.group(1).lower()] = len(args)
    return out


def c_prototypes(path: Path) -> list:
    """[(name, [arg, ...]), ...] for every SpfftError-returning prototype,
    in declaration order."""
    joined = re.sub(r"\s+", " ", strip_c_comments(path.read_text()))
    return [
        (m.group(1), [a.strip() for a in m.group(2).split(",") if a.strip()])
        for m in re.finditer(r"SpfftError\s+(spfft_\w+)\s*\(([^)]*)\)\s*;", joined)
    ]


def c_functions(paths=C_HEADER_PATHS) -> dict:
    """{lowercased name: arg count} across the given headers."""
    out = {}
    for path in paths:
        for name, args in c_prototypes(path):
            out[name.lower()] = len(args)
    return out


C_CONSTANT_HEADER_NAMES = ("errors.h", "types.h")
C_CONSTANT_HEADER_PATHS = tuple(
    ROOT / "native" / "include" / "spfft" / name for name in C_CONSTANT_HEADER_NAMES
)


def fortran_constants(path: Path = F90_PATH) -> dict:
    """{NAME: value} for every ``integer(c_int), parameter`` constant.

    Handles both one-constant-per-statement declarations and the reference
    module's continuation-list style, where a single ``parameter ::`` heads
    many '&'-continued ``NAME = value`` entries
    (reference: include/spfft/spfft.f90:54-110)."""
    text = join_continuations(path.read_text())
    out = {}
    for stmt in re.finditer(
        r"integer\s*\(\s*c_int\s*\)\s*,\s*parameter\s*::([^\n]*)",
        text,
        re.IGNORECASE,
    ):
        for m in re.finditer(r"(SPFFT_\w+)\s*=\s*(-?\d+)", stmt.group(1)):
            out[m.group(1)] = int(m.group(2))
    return out


def c_enum_constants(paths=C_CONSTANT_HEADER_PATHS) -> dict:
    """{NAME: value} for every SPFFT_* enumerator, explicit or implicit."""
    out = {}
    for path in paths:
        text = strip_c_comments(path.read_text())
        for body in re.finditer(r"\benum\s+\w+\s*\{([^}]*)\}", text):
            counter = 0
            for entry in body.group(1).split(","):
                m = re.match(r"\s*(SPFFT_[A-Z0-9_]+)\s*(?:=\s*(-?\d+))?\s*$", entry)
                if m is None:
                    continue
                if m.group(2) is not None:
                    counter = int(m.group(2))
                out[m.group(1)] = counter
                counter += 1
    return out


REFERENCE_INCLUDE = Path("/root/reference/include/spfft")


def surface_names(include_dir: Path) -> dict:
    """{name: arg count} across every C header (.h) in ``include_dir``."""
    out = {}
    for path in sorted(include_dir.glob("*.h")):
        for name, args in c_prototypes(path):
            out[name] = len(args)
    return out


def reference_only_names(reference_dir: Path = REFERENCE_INCLUDE) -> list:
    """Reference C API names (with arity) absent from the shipped headers.

    The parity contract: every reference prototype must exist here with the
    same argument count — extensions beyond the reference are fine, holes are
    not. Returns [] when the surface is complete (or the reference tree is
    not present to compare against).
    """
    if not reference_dir.is_dir():
        return []
    ref = surface_names(reference_dir)
    ours = surface_names(C_HEADER_PATHS[0].parent)
    return sorted(
        f"{name}/{arity}"
        for name, arity in ref.items()
        if name not in ours or ours[name] != arity
    )


if __name__ == "__main__":
    import sys

    if not REFERENCE_INCLUDE.is_dir():
        print("C API parity check SKIPPED: reference tree not present at "
              f"{REFERENCE_INCLUDE}")
        sys.exit(0)
    missing = reference_only_names()
    if missing:
        print("reference-only C API names (name/arity):")
        for entry in missing:
            print(" ", entry)
        sys.exit(1)
    print(f"C API surface complete: {len(surface_names(REFERENCE_INCLUDE))} "
          "reference names all present with matching arity")
