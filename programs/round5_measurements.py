"""Round-5 on-chip batch 1: pencil row-granular fix + P=1 overhead A/B.

One process, one device claim (the round-3 discipline). Arms:

1. ``local_c2c_256_s15`` — the matched local baseline (chain 384), shared
   reference arm for both comparisons below.
2. ``pencil1x1_c2c_256_sph15_r5`` — the round-5 row-granular pencil engine on
   the chip. Round-4 row: 1.28 s/pair (~230x local, element-scatter bound,
   ROADMAP 8b). Done-criterion: within ~1.5x the local arm. A short chain
   runs first (watchdog safety if the fix regressed); a long chain re-pins
   when the short one lands under 50 ms/pair.
3. ``dist1_c2c_256_s15`` — 1-D mesh P=1 distributed, same config/chain as the
   local arm (VERDICT r4 weak-item 4: 7.5-8.1 ms recorded vs 5.52 local while
   round-3 text claimed ~7%; exchange is specialized away at P=1, so any gap
   is pure engine overhead). One consistent matched pair decides it.

Results append incrementally to ``bench_results/round5_onchip.json``.

Usage: python programs/round5_measurements.py [--quick]
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_onchip.json"
)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true", help="short chains (smoke)")
    args = ap.parse_args()

    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round5_measurements", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def flops_pair(dim):
        n = dim**3
        return 2 * 5.0 * n * np.log2(n)

    def chain_time(ex, re0, im0, chain):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                sre, sim = ex.trace_backward(*carry, phase=ph)
                return ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph), None

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, wim = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, _ = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    dim = 256
    CH = 48 if args.quick else 384
    trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
    rng = np.random.default_rng(0)

    # ---- 1: matched local baseline ----
    local_ms = None
    try:
        t = Transform(
            ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
            indices=trip, dtype=np.float32, engine="mxu",
        )
        ex = t._exec
        n = len(trip)
        re0 = ex.put(rng.standard_normal(n).astype(np.float32))
        im0 = ex.put(rng.standard_normal(n).astype(np.float32))
        best, err = chain_time(ex, re0, im0, CH)
        local_ms = best * 1e3
        record({
            "name": "local_c2c_256_s15", "chain": CH,
            "ms_per_pair": round(best * 1e3, 3),
            "gflops": round(flops_pair(dim) / best / 1e9, 1),
            "roundtrip_err": err,
        })
    except Exception as e:
        record({"name": "local_c2c_256_s15", "error": f"{type(e).__name__}: {e}"})

    # ---- 2: pencil 1x1, short probe then long re-pin ----
    try:
        t = DistributedTransform(
            ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, trip,
            mesh=sp.make_fft_mesh2(1, 1), dtype=np.float32, engine="mxu",
        )
        ex = t._exec
        vals = (
            rng.standard_normal(t.num_local_elements(0))
            + 1j * rng.standard_normal(t.num_local_elements(0))
        ).astype(np.complex64)
        pairs = ex.pad_values([vals])
        probe_chain = 16 if args.quick else 48
        best, err = chain_time(ex, pairs[0], pairs[1], probe_chain)
        row = {
            "name": "pencil1x1_c2c_256_sph15_r5_probe", "chain": probe_chain,
            "ms_per_pair": round(best * 1e3, 3),
            "gflops": round(flops_pair(dim) / best / 1e9, 1),
            "roundtrip_err": err, "engine": t._engine,
            "r4_row_ms": 1280.0,
        }
        record(row)
        if best * 1e3 < 50 and not args.quick:
            best, err = chain_time(ex, pairs[0], pairs[1], CH)
            record({
                "name": "pencil1x1_c2c_256_sph15_r5", "chain": CH,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
                "vs_local": (
                    round(best * 1e3 / local_ms, 3) if local_ms else None
                ),
            })
    except Exception as e:
        record({
            "name": "pencil1x1_c2c_256_sph15_r5",
            "error": f"{type(e).__name__}: {e}",
        })

    # ---- 3: dist P=1 (1-D mesh), matched arm ----
    try:
        t = DistributedTransform(
            ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, trip,
            mesh=sp.make_fft_mesh(1), dtype=np.float32, engine="mxu",
        )
        ex = t._exec
        vals = (
            rng.standard_normal(t.num_local_elements(0))
            + 1j * rng.standard_normal(t.num_local_elements(0))
        ).astype(np.complex64)
        pairs = ex.pad_values([vals])
        best, err = chain_time(ex, pairs[0], pairs[1], CH)
        record({
            "name": "dist1_c2c_256_s15", "chain": CH,
            "ms_per_pair": round(best * 1e3, 3),
            "gflops": round(flops_pair(dim) / best / 1e9, 1),
            "roundtrip_err": err,
            "vs_local": round(best * 1e3 / local_ms, 3) if local_ms else None,
        })
    except Exception as e:
        record({"name": "dist1_c2c_256_s15", "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
