"""Project lint: import hygiene + env-knob/docs + stage-scope consistency.

No third-party linter exists in this environment, so the checks the advisor
kept flagging are enforced here with the stdlib ast module:

1. duplicate imports — the same module/name imported more than once in one
   file (the round-3/4 nit class in capi.py),
2. unused imports — an imported name never referenced in the file
   (``# noqa: F401`` on the import line exempts re-exports),
3. env-knob consistency — every ``SPFFT_TPU_*`` knob read by the package
   must be documented in docs/details.md, and every documented knob must
   still exist in code (dead-doc detection),
4. stage-scope consistency — every ``jax.named_scope`` label in an engine
   pipeline comes from the canonical ``spfft_tpu.obs.STAGES`` list, and every
   listed stage appears in at least one engine (same both-ways style as the
   env-knob rule; keeps profiler traces attributable against one vocabulary),
5. fault-site consistency — every ``faults.site(...)`` call in the package
   names a site registered in the canonical ``spfft_tpu.faults.SITES``
   vocabulary, every registered site is threaded through the package at
   least once, and every site is documented in docs/details.md (the chaos
   suite's arm-every-site sweep is only exhaustive if the vocabulary is),
6. trace-event consistency — every ``trace.event/span/operation(...)`` call
   in the package names an event registered in the canonical
   ``spfft_tpu.obs.trace.EVENTS`` vocabulary, and every registered event is
   emitted by at least one package call site (same both-ways rule; keeps
   flight-recorder streams and their consumers on one vocabulary),
7. verify-check consistency — the canonical ``spfft_tpu.verify.CHECKS``
   vocabulary matches the ``CHECK_FNS`` implementation registry exactly
   (every registered check implemented, every implementation registered)
   and every check is documented in docs/details.md — the ABFT layer's
   instance of the same both-ways contract,
8. perf-stage consistency — the perf layer's ``MODELED_STAGES``
   (``spfft_tpu/obs/perf.py``) matches the engine-pipeline subset of
   ``obs.STAGES`` exactly both ways: every modeled stage is canonical and
   appears in an engine pipeline, and every engine-pipeline stage carries a
   flop/byte model — so perf reports can never emit or omit a stage the
   engines disagree about (the tuning-only trial phases are exempt: they
   are harness stages, not pipeline stages),
9. IR-node consistency — the stage-graph IR's node vocabulary
   (``spfft_tpu/ir/graph.py`` ``NODES``) matches ``obs.STAGES`` and
   ``perf.MODELED_STAGES`` both ways: every IR node is a canonical,
   perf-modeled stage, and every modeled engine stage is lowerable as an IR
   node — an IR stage can never silently escape profiler attribution or
   perf accounting (the same contract as SITES/EVENTS).

Exit status is nonzero on any finding; ci.sh runs this as its lint stage.
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
PACKAGE_DIRS = ("spfft_tpu",)
LINT_DIRS = ("spfft_tpu", "programs", "tests")
DOCS = ROOT / "docs" / "details.md"

# knobs that are deliberately undocumented in the user-facing table: test /
# driver / measurement internals, documented where they are used
INTERNAL_KNOBS = {
    "SPFFT_TPU_DRYRUN_BUDGET_S",
    "SPFFT_TPU_MEASURE_INIT_BUDGET_S",
    "SPFFT_TPU_NATIVE_TEST_BUDGET_S",
    "SPFFT_TPU_FUZZ_SEED",  # test-only: parity-fuzz seed offset (documented
    # where it is read, tests/test_engine_parity_fuzz.py)
}


def iter_py_files():
    for d in LINT_DIRS:
        yield from sorted((ROOT / d).rglob("*.py"))


def _import_forms(node):
    """Canonical (form, bound-name) pairs for an import statement."""
    out = []
    if isinstance(node, ast.Import):
        for a in node.names:
            form = f"import {a.name}" + (f" as {a.asname}" if a.asname else "")
            out.append((form, (a.asname or a.name).split(".")[0]))
    elif isinstance(node, ast.ImportFrom):
        if node.module == "__future__":
            return []
        mod = "." * node.level + (node.module or "")
        for a in node.names:
            if a.name == "*":
                continue
            form = f"from {mod} import {a.name}" + (
                f" as {a.asname}" if a.asname else ""
            )
            out.append((form, a.asname or a.name))
    return out


def _walk_scope(body):
    """Statements of one scope, not descending into nested function/class
    bodies (lazy function-scope imports are a deliberate pattern here —
    duplicates only count within a single scope)."""
    for stmt in body:
        yield stmt
        if isinstance(
            stmt, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
        ):
            continue
        for field in ("body", "orelse", "finalbody", "handlers"):
            sub = getattr(stmt, field, None)
            if not sub:
                continue
            for child in sub:
                if isinstance(child, ast.ExceptHandler):
                    yield from _walk_scope(child.body)
                else:
                    yield from _walk_scope([child])


def check_imports(path: Path, findings: list):
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        findings.append(f"{path}: syntax error: {e}")
        return
    lines = src.splitlines()

    def exempt(node):
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        return "noqa" in line

    # ---- duplicates, per scope (class bodies count as their own scope) ----
    scopes = [tree.body]
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            scopes.append(node.body)
    for body in scopes:
        seen = {}
        for stmt in _walk_scope(body):
            if not isinstance(stmt, (ast.Import, ast.ImportFrom)):
                continue
            for form, _name in _import_forms(stmt):
                if form in seen and not exempt(stmt):
                    findings.append(
                        f"{path}:{stmt.lineno}: duplicate {form!r} "
                        f"(first at line {seen[form]})"
                    )
                seen.setdefault(form, stmt.lineno)

    # ---- unused, module scope only ----
    bound = []
    for stmt in _walk_scope(tree.body):
        if isinstance(stmt, (ast.Import, ast.ImportFrom)) and not exempt(stmt):
            bound.extend(
                (name, stmt.lineno) for _form, name in _import_forms(stmt)
            )
    used = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Assign):
            # __all__ strings count as uses (re-export surface)
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    for el in ast.walk(node.value):
                        if isinstance(el, ast.Constant) and isinstance(
                            el.value, str
                        ):
                            used.add(el.value)
    for name, lineno in bound:
        if name not in used and name != "_":
            findings.append(f"{path}:{lineno}: unused import {name!r}")


KNOB_RE = re.compile(r"SPFFT_TPU_[A-Z0-9_]+")


def check_env_knobs(findings: list):
    in_code = set()
    for d in LINT_DIRS:
        for path in sorted((ROOT / d).rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            text = path.read_text()
            if d in PACKAGE_DIRS:
                # the package defines the knob surface: every SPFFT_TPU_*
                # string in it is an env knob (indirected through *_ENV
                # constants, so line-level environ matching misses them)
                in_code |= set(KNOB_RE.findall(text))
            else:
                # programs/tests: only env READS count — SPFFT_TPU_* also
                # names C macros (version.h) and CMake options there
                for line in text.splitlines():
                    if "environ" in line or "getenv" in line:
                        in_code |= set(KNOB_RE.findall(line))
    documented = set(KNOB_RE.findall(DOCS.read_text()))
    for knob in sorted(in_code - documented - INTERNAL_KNOBS):
        findings.append(
            f"env knob {knob} is read by the package but not documented in "
            f"{DOCS.relative_to(ROOT)}"
        )
    for knob in sorted(documented - in_code):
        findings.append(
            f"env knob {knob} is documented in {DOCS.relative_to(ROOT)} but "
            "no longer read by the package"
        )


# The engine pipeline modules: every named_scope label inside them must come
# from obs.STAGES, and every STAGES entry must appear in at least one of them.
ENGINE_FILES = (
    "spfft_tpu/execution.py",
    "spfft_tpu/execution_mxu.py",
    "spfft_tpu/parallel/execution.py",
    "spfft_tpu/parallel/execution_mxu.py",
    "spfft_tpu/parallel/pencil2.py",
    "spfft_tpu/parallel/pencil2_mxu.py",
)
# The autotuner's trial runner labels its phases from the same canonical
# vocabulary (the "tune warmup"/"tune trial" stages), under the same
# both-ways rule as the engines.
TUNING_FILES = ("spfft_tpu/tuning/runner.py",)
STAGES_FILE = "spfft_tpu/obs/stages.py"


def _canonical_stages() -> tuple:
    """STAGES from obs/stages.py via ast (import-free: lint must not pull jax)."""
    tree = ast.parse((ROOT / STAGES_FILE).read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "STAGES" for t in node.targets
        ):
            return tuple(ast.literal_eval(node.value))
    raise AssertionError(f"no STAGES assignment in {STAGES_FILE}")


def _pipeline_strings(tree) -> set:
    """String constants of an engine/tuning file, EXCLUDING those inside the
    ``stage_accounting`` perf hooks: the hooks restate every stage name for
    the flop/byte model, so counting them would let the coverage directions
    satisfy themselves — a stage deleted from every ``named_scope`` would
    still look 'used' because its accounting row names it."""
    skip: set = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
            and node.name == "stage_accounting"
        ):
            for sub in ast.walk(node):
                skip.add(id(sub))
    return {
        node.value
        for node in ast.walk(tree)
        if isinstance(node, ast.Constant)
        and isinstance(node.value, str)
        and id(node) not in skip
    }


def check_stage_scopes(findings: list):
    stages = _canonical_stages()
    if len(set(stages)) != len(stages):
        findings.append(f"{STAGES_FILE}: duplicate entries in STAGES")
    used: dict = {}  # literal named_scope labels -> first file:line
    strings: set = set()  # pipeline string constants in engine files (covers
    # labels selected dynamically, e.g. _y_stage_scope's variants; the
    # stage_accounting hooks are excluded — see _pipeline_strings)
    for rel in ENGINE_FILES + TUNING_FILES:
        path = ROOT / rel
        tree = ast.parse(path.read_text())
        strings |= _pipeline_strings(tree)
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "named_scope"
            ):
                continue
            if node.args and isinstance(node.args[0], ast.Constant):
                label = node.args[0].value
                used.setdefault(label, f"{rel}:{node.args[0].lineno}")
    for label, where in sorted(used.items()):
        if label not in stages:
            findings.append(
                f"{where}: named_scope {label!r} is not in the canonical "
                f"stage list ({STAGES_FILE})"
            )
    for stage in stages:
        if stage not in strings:
            findings.append(
                f"{STAGES_FILE}: stage {stage!r} appears in no engine or "
                f"tuning pipeline ({', '.join(ENGINE_FILES + TUNING_FILES)})"
            )


# The fault-injection plane: every faults.site(...) call must name a site
# registered in SITES (spfft_tpu/faults/plane.py), every registered site must
# be threaded through the package, and every site must appear in the docs.
FAULTS_PLANE_FILE = "spfft_tpu/faults/plane.py"


def _canonical_sites() -> tuple:
    """SITES from faults/plane.py via ast (import-free, like STAGES)."""
    tree = ast.parse((ROOT / FAULTS_PLANE_FILE).read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "SITES" for t in node.targets
        ):
            return tuple(ast.literal_eval(node.value))
    raise AssertionError(f"no SITES assignment in {FAULTS_PLANE_FILE}")


def check_fault_sites(findings: list):
    sites = _canonical_sites()
    if len(set(sites)) != len(sites):
        findings.append(f"{FAULTS_PLANE_FILE}: duplicate entries in SITES")
    used: dict = {}  # site name -> first package file:line that arms it
    for d in PACKAGE_DIRS:
        for path in sorted((ROOT / d).rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(ROOT)
            if str(rel) == FAULTS_PLANE_FILE:
                continue  # the registry itself is not a threading site
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "site"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "faults"
                ):
                    continue
                where = f"{rel}:{node.lineno}"
                if not (node.args and isinstance(node.args[0], ast.Constant)):
                    findings.append(
                        f"{where}: faults.site(...) must take a literal site "
                        "name (lint cannot check dynamic names)"
                    )
                    continue
                name = node.args[0].value
                if name not in sites:
                    findings.append(
                        f"{where}: fault site {name!r} is not registered in "
                        f"the canonical vocabulary ({FAULTS_PLANE_FILE})"
                    )
                used.setdefault(name, where)
    for name in sites:
        if name not in used:
            findings.append(
                f"{FAULTS_PLANE_FILE}: site {name!r} is registered but "
                "threaded through no package code path"
            )
    docs_text = DOCS.read_text()
    for name in sites:
        if name not in docs_text:
            findings.append(
                f"fault site {name!r} is not documented in "
                f"{DOCS.relative_to(ROOT)}"
            )


# The execution-trace event vocabulary (spfft_tpu/obs/trace.py EVENTS): every
# trace.event/span/operation call in the package must name a registered
# event, and every registered event must be emitted by at least one package
# call site — the same both-ways contract as STAGES and SITES.
TRACE_FILE = "spfft_tpu/obs/trace.py"
TRACE_EMITTERS = ("event", "span", "operation")


def _canonical_events() -> tuple:
    """EVENTS from obs/trace.py via ast (import-free, like STAGES/SITES)."""
    tree = ast.parse((ROOT / TRACE_FILE).read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "EVENTS" for t in node.targets
        ):
            return tuple(ast.literal_eval(node.value))
    raise AssertionError(f"no EVENTS assignment in {TRACE_FILE}")


def _is_trace_receiver(value) -> bool:
    """Whether a call receiver is the trace module (``trace.x`` after a
    ``from .obs import trace``, or a dotted ``obs.trace.x``)."""
    if isinstance(value, ast.Name):
        return value.id == "trace"
    return isinstance(value, ast.Attribute) and value.attr == "trace"


def check_trace_events(findings: list):
    events = _canonical_events()
    if len(set(events)) != len(events):
        findings.append(f"{TRACE_FILE}: duplicate entries in EVENTS")
    used: dict = {}  # event name -> first package file:line that emits it
    for d in PACKAGE_DIRS:
        for path in sorted((ROOT / d).rglob("*.py")):
            if "__pycache__" in path.parts:
                continue
            rel = path.relative_to(ROOT)
            if str(rel) == TRACE_FILE:
                continue  # the recorder itself is not an emission site
            tree = ast.parse(path.read_text())
            for node in ast.walk(tree):
                if not (
                    isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in TRACE_EMITTERS
                    and _is_trace_receiver(node.func.value)
                ):
                    continue
                where = f"{rel}:{node.lineno}"
                if not (node.args and isinstance(node.args[0], ast.Constant)):
                    findings.append(
                        f"{where}: trace.{node.func.attr}(...) must take a "
                        "literal event name (lint cannot check dynamic names)"
                    )
                    continue
                name = node.args[0].value
                if name not in events:
                    findings.append(
                        f"{where}: trace event {name!r} is not registered in "
                        f"the canonical vocabulary ({TRACE_FILE})"
                    )
                used.setdefault(name, where)
    for name in events:
        if name not in used:
            findings.append(
                f"{TRACE_FILE}: event {name!r} is registered but emitted by "
                "no package code path"
            )


# The ABFT check vocabulary (spfft_tpu/verify/checks.py CHECKS): the tuple
# and the CHECK_FNS implementation registry must agree exactly, and every
# check must be documented — the verify layer's both-ways contract.
VERIFY_CHECKS_FILE = "spfft_tpu/verify/checks.py"


def _canonical_checks() -> tuple:
    """CHECKS and CHECK_FNS keys from verify/checks.py via ast (import-free,
    like STAGES/SITES/EVENTS)."""
    tree = ast.parse((ROOT / VERIFY_CHECKS_FILE).read_text())
    checks = fns = None
    for node in tree.body:
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Name) and t.id == "CHECKS":
                checks = tuple(ast.literal_eval(node.value))
            if isinstance(t, ast.Name) and t.id == "CHECK_FNS":
                if not isinstance(node.value, ast.Dict):
                    raise AssertionError(
                        f"CHECK_FNS in {VERIFY_CHECKS_FILE} must be a dict literal"
                    )
                fns = tuple(
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant)
                )
    if checks is None or fns is None:
        raise AssertionError(
            f"no CHECKS/CHECK_FNS assignments in {VERIFY_CHECKS_FILE}"
        )
    return checks, fns


def check_verify_checks(findings: list):
    checks, fns = _canonical_checks()
    if len(set(checks)) != len(checks):
        findings.append(f"{VERIFY_CHECKS_FILE}: duplicate entries in CHECKS")
    for name in checks:
        if name not in fns:
            findings.append(
                f"{VERIFY_CHECKS_FILE}: check {name!r} is registered in CHECKS "
                "but has no CHECK_FNS implementation"
            )
    for name in fns:
        if name not in checks:
            findings.append(
                f"{VERIFY_CHECKS_FILE}: CHECK_FNS implements {name!r} but it "
                "is not registered in CHECKS"
            )
    docs_text = DOCS.read_text()
    for name in checks:
        if name not in docs_text:
            findings.append(
                f"verify check {name!r} is not documented in "
                f"{DOCS.relative_to(ROOT)}"
            )


# The perf layer's modeled-stage vocabulary (spfft_tpu/obs/perf.py
# MODELED_STAGES): must equal the engine-pipeline subset of STAGES exactly —
# both ways, like every other vocabulary here. Tuning-only stages (threaded
# through TUNING_FILES, never an engine pipeline) are exempt.
PERF_FILE = "spfft_tpu/obs/perf.py"


def _canonical_modeled_stages() -> tuple:
    """MODELED_STAGES from obs/perf.py via ast (import-free, like STAGES)."""
    tree = ast.parse((ROOT / PERF_FILE).read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == "MODELED_STAGES"
            for t in node.targets
        ):
            return tuple(ast.literal_eval(node.value))
    raise AssertionError(f"no MODELED_STAGES assignment in {PERF_FILE}")


def check_perf_stages(findings: list):
    stages = _canonical_stages()
    modeled = _canonical_modeled_stages()
    if len(set(modeled)) != len(modeled):
        findings.append(f"{PERF_FILE}: duplicate entries in MODELED_STAGES")
    engine_strings: set = set()
    for rel in ENGINE_FILES:
        # accounting hooks excluded (_pipeline_strings): membership here must
        # mean "the compiled pipeline tags this stage", not "the perf model
        # mentions it" — otherwise this check could never catch drift
        engine_strings |= _pipeline_strings(ast.parse((ROOT / rel).read_text()))
    engine_stages = [s for s in stages if s in engine_strings]
    for name in modeled:
        if name not in stages:
            findings.append(
                f"{PERF_FILE}: modeled stage {name!r} is not in the canonical "
                f"stage list ({STAGES_FILE})"
            )
        elif name not in engine_stages:
            findings.append(
                f"{PERF_FILE}: modeled stage {name!r} appears in no engine "
                f"pipeline ({', '.join(ENGINE_FILES)})"
            )
    for name in engine_stages:
        if name not in modeled:
            findings.append(
                f"{STAGES_FILE}: engine stage {name!r} carries no flop/byte "
                f"model in {PERF_FILE} (MODELED_STAGES)"
            )


# The stage-graph IR's node vocabulary (spfft_tpu/ir/graph.py NODES): must
# match obs.STAGES membership and perf.MODELED_STAGES exactly both ways —
# the IR is the layer engines execute through, so a node outside the
# canonical/modeled vocabularies would be a stage traces and perf reports
# cannot account for, and a modeled stage missing from NODES would be a
# pipeline stage the IR cannot express.
IR_GRAPH_FILE = "spfft_tpu/ir/graph.py"


def _canonical_ir_nodes() -> tuple:
    """NODES from ir/graph.py via ast (import-free, like STAGES)."""
    return _literal_tuple(IR_GRAPH_FILE, "NODES")


def check_ir_nodes(findings: list):
    stages = _canonical_stages()
    modeled = _canonical_modeled_stages()
    nodes = _canonical_ir_nodes()
    if len(set(nodes)) != len(nodes):
        findings.append(f"{IR_GRAPH_FILE}: duplicate entries in NODES")
    for name in nodes:
        if name not in stages:
            findings.append(
                f"{IR_GRAPH_FILE}: IR node {name!r} is not in the canonical "
                f"stage list ({STAGES_FILE})"
            )
        if name not in modeled:
            findings.append(
                f"{IR_GRAPH_FILE}: IR node {name!r} carries no flop/byte "
                f"model in {PERF_FILE} (MODELED_STAGES)"
            )
    for name in modeled:
        if name not in nodes:
            findings.append(
                f"{PERF_FILE}: modeled stage {name!r} is not an IR node "
                f"({IR_GRAPH_FILE} NODES) — the stage graph cannot express it"
            )


# The plan-card ``ir`` section schema (obs/plancard.py IR_SECTION_KEYS) is a
# deliberate mirror of the source-of-truth literal in ir/compile.py IR_KEYS
# (plancard stays import-free): the two tuples must be identical, or cards
# missing a newly added key would silently pass schema validation.
IR_COMPILE_FILE = "spfft_tpu/ir/compile.py"
PLANCARD_FILE = "spfft_tpu/obs/plancard.py"


def _literal_tuple(relpath: str, name: str) -> tuple:
    """A module-level tuple literal via ast (import-free, like STAGES)."""
    tree = ast.parse((ROOT / relpath).read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id == name for t in node.targets
        ):
            return tuple(ast.literal_eval(node.value))
    raise AssertionError(f"no {name} assignment in {relpath}")


def check_ir_card_keys(findings: list):
    ir_keys = _literal_tuple(IR_COMPILE_FILE, "IR_KEYS")
    card_keys = _literal_tuple(PLANCARD_FILE, "IR_SECTION_KEYS")
    if ir_keys != card_keys:
        findings.append(
            f"{PLANCARD_FILE}: IR_SECTION_KEYS {card_keys!r} does not match "
            f"{IR_COMPILE_FILE} IR_KEYS {ir_keys!r} — the card validator "
            f"would accept cards missing (or carrying stale) ir keys"
        )


def main() -> int:
    findings: list = []
    for path in iter_py_files():
        if "__pycache__" in path.parts:
            continue
        check_imports(path, findings)
    check_env_knobs(findings)
    check_stage_scopes(findings)
    check_fault_sites(findings)
    check_trace_events(findings)
    check_verify_checks(findings)
    check_perf_stages(findings)
    check_ir_nodes(findings)
    check_ir_card_keys(findings)
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} lint finding(s)", file=sys.stderr)
        return 1
    print("lint clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
