"""Project lint — thin shim over ``spfft_tpu.analysis`` checkers 1-9.

The nine ad-hoc AST checks that used to live here (635 lines: import
hygiene, env-knob docs, stage scopes, fault sites, trace events, verify
checks, perf stages, IR nodes) are now checkers SA001-SA009 of the
pluggable static-analysis engine (``spfft_tpu/analysis/``), with the same
vocabulary contracts enforced both ways. This shim keeps ``./ci.sh lint``
and muscle memory working: it runs exactly the ported checkers through the
same gate (baseline applied, ``# noqa: <CODE>`` suppression honored) and
exits 3 on any new finding.

The full gate — including the deep checkers (typed errors, lock order,
donation safety, jit purity, knob registry) — is ``programs/analyze.py`` /
``./ci.sh analyze``.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent))

from analyze import main as analyze_main  # noqa: E402


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    for code in (f"SA00{i}" for i in range(1, 10)):
        argv += ["--only", code]
    return analyze_main(argv)


if __name__ == "__main__":
    sys.exit(main())
