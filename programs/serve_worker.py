"""RPC serving worker: one host of a multi-host transform-serving fleet.

Spawned by ``spfft_tpu.hostmesh.spawn_workers`` (or by hand): boots jax on
this host (optionally joining a ``jax.distributed`` multi-controller run),
warm-starts tuning wisdom from the fleet bundle
(``SPFFT_TPU_HOSTS_WISDOM_BUNDLE``), stands up a local
``serve.TransformService`` behind a length-prefixed-JSON ``RpcServer``
(``spfft_tpu.serve.rpc``), and writes a ready file naming the bound port —
the parent's boot handshake. Every ``SPFFT_TPU_*`` knob arrives via the
environment (``hostmesh.child_env`` propagates the parent's), so lockdep
arming, chaos specs and serving knobs govern workers exactly as they do a
single-process run.

Exits cleanly on the RPC ``shutdown`` op (so exit hooks — the lockdep
report dump — run); a SIGKILL is the chaos scenario the cluster front's
heartbeat/host-lost ladder exists for.

Usage: serve_worker.py --host-id 0 --port 0 --ready-file /tmp/w0.json
       [--coordinator host:port --num-processes N --process-id I]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import threading
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--host-id", type=int, default=0)
    p.add_argument("--port", type=int, default=0,
                   help="RPC listen port (0 = OS-assigned)")
    p.add_argument("--ready-file", default=None,
                   help="write a JSON ready record here once serving")
    p.add_argument("--coordinator", default=None,
                   help="jax.distributed coordinator host:port (joins a "
                   "multi-controller run when given)")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import spfft_tpu  # noqa: F401  (arms lockdep/faults from the env)
    from spfft_tpu import hostmesh
    from spfft_tpu.serve import TransformService
    from spfft_tpu.serve.rpc import RpcServer

    topology = None
    if args.coordinator is not None:
        topology = hostmesh.boot(
            args.coordinator, args.num_processes, args.process_id
        )
    warm = hostmesh.warm_start()

    shutdown = threading.Event()
    service = TransformService(start=True)
    server = RpcServer(
        service, port=args.port, on_shutdown=shutdown.set
    )

    ready = {
        "host_id": int(args.host_id),
        "pid": os.getpid(),
        "port": server.port,
        "wisdom_warm_start": list(warm),
        "topology": topology,
        "env_knobs": sorted(
            k for k in os.environ if k.startswith("SPFFT_TPU_")
        ),
    }
    if args.ready_file:
        tmp = Path(str(args.ready_file) + ".tmp")
        tmp.write_text(json.dumps(ready, indent=1))
        tmp.rename(args.ready_file)  # atomic: the parent never reads a torn file
    print(f"SPFFT_WORKER_READY {json.dumps(ready)}", flush=True)

    # serve until a peer sends the shutdown op (bounded waits: the loop
    # re-checks twice a second so signals/KeyboardInterrupt land promptly)
    try:
        while not shutdown.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    server.close()
    service.close(drain=False)
    return 0


if __name__ == "__main__":
    sys.exit(main())
