"""Round-4 on-chip batch 3: staging-bandwidth probe + final re-pins.

1. Host<->device staging bandwidth through the tunnel at several chunk
   sizes — quantifies the floor under the f64 512^3 host-facing pair
   (device compute measured 1.5 s; the host-facing pair 88-164 s, i.e.
   ~98% staging) so BASELINE.md can report the split honestly.
2. 512^3 default re-pin with the measured auto G rule (G=8 at 512).
3. Headline re-pin with embedded matrices (the size-dependent operand rule).

Appends to bench_results/round4_onchip3.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round4_onchip3.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round4_measurements3", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import spfft_tpu as sp
    from spfft_tpu import ProcessingUnit, ScalingType, Transform, TransformType

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    # ---- 1: staging bandwidth probe ----
    for mb in (64, 256, 1024):
        try:
            arr = np.random.default_rng(0).standard_normal((mb << 20) // 8)
            t0 = time.perf_counter()
            d = jax.device_put(arr, dev)
            d.block_until_ready()
            # a scalar fetch is the only reliable fence on this tunnel
            float(jax.device_get(d[0]))
            up = time.perf_counter() - t0
            t0 = time.perf_counter()
            _ = np.asarray(d)
            down = time.perf_counter() - t0
            record({
                "name": f"staging_bandwidth_{mb}mb",
                "put_s": round(up, 2),
                "put_mb_s": round(mb / up, 1),
                "fetch_s": round(down, 2),
                "fetch_mb_s": round(mb / down, 1),
            })
            del d
        except Exception as e:
            record({"name": f"staging_bandwidth_{mb}mb",
                    "error": f"{type(e).__name__}: {e}"})

    # ---- 2+3: re-pins under the shipped auto rules ----
    def time_chain(ex, re0, im0, chain):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                sre, sim = ex.trace_backward(*carry, phase=ph)
                return (
                    ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph),
                    None,
                )

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, wim = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, cim = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        return best

    def measure(name, dim, chain):
        try:
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
            t = Transform(
                ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
                indices=trip, dtype=np.float32, engine="mxu",
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            n = len(trip)
            re0 = ex.put(rng.standard_normal(n).astype(np.float32))
            im0 = ex.put(rng.standard_normal(n).astype(np.float32))
            best = time_chain(ex, re0, im0, chain)
            ntot = dim**3
            record({
                "name": name, "dim": dim,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(2 * 5.0 * ntot * np.log2(ntot) / best / 1e9, 1),
                "blocked_buckets": len(
                    getattr(ex, "_sparse_y_blocked", None) or ()
                ),
                "n_operands": len(getattr(ex, "phase_operands", ())),
            })
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})

    measure("c2c_256_s15_final", 256, 384)
    measure("c2c_512_sph15_final", 512, 48)

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
