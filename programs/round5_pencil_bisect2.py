"""Round-5 pencil bisection, part 2: the slow stage IS inside shard_map.

Part 1 (round5_pencil_bisect.json): every backward compute stage under plain
jit (static shard indices) sums to 4.4 ms; the identical pipeline under the
1x1 shard_map runs 980 ms/pair. A follow-up probe refuted the traced-index
gather theory (const/traced/operand indices all gather alike). This part
times cumulative prefixes of the REAL per-shard program — lax.switch
decompress, phase tables, traced axis_index-derived maps — under the REAL
shard_map, to isolate which construct explodes.

Appends to bench_results/round5_pencil_bisect2.json.
"""
from __future__ import annotations

import functools
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_pencil_bisect2.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round5_pencil_bisect2", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import spfft_tpu as sp
    from spfft_tpu import DistributedTransform, ProcessingUnit, TransformType
    from spfft_tpu.ops import fft as offt, lanecopy
    from spfft_tpu.parallel.pencil2 import AX1, AX2

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    dim = 256
    trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
    t = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, trip,
        mesh=sp.make_fft_mesh2(1, 1), dtype=np.float32, engine="mxu",
    )
    ex = t._exec
    p = ex.params
    rt = ex.real_dtype
    S, Z = ex._S, p.dim_z
    Ax, Lz, Ly, P1, P2 = ex._Ax, ex._Lz, ex._Ly, ex.P1, ex.P2
    prec = ex._precision
    rng = np.random.default_rng(0)

    vals = (
        rng.standard_normal(t.num_local_elements(0))
        + 1j * rng.standard_normal(t.num_local_elements(0))
    ).astype(np.complex64)
    vre, vim = ex.pad_values([vals])

    REPS = 48
    both = (AX1, AX2)
    specs_v = P(both, None)

    def fold_to_values(x, n):
        flat = x.ravel()
        if flat.shape[0] >= n:
            return flat[:n].astype(rt)
        return jnp.pad(flat, (0, n - flat.shape[0])).astype(rt)

    def make_sm(stage_fn):
        """shard_map'd (1, V)-pair -> (1, V)-pair program running stage_fn on
        per-shard data with the REAL traced axis indices."""

        def body(a, b):
            a_me = jax.lax.axis_index(AX1)
            b_me = jax.lax.axis_index(AX2)
            s_me = a_me * P2 + b_me
            oa, ob = stage_fn(a[0], b[0], a_me, b_me, s_me)
            n = a.shape[1]
            return fold_to_values(oa, n)[None], fold_to_values(ob, n)[None]

        return functools.partial(
            jax.shard_map, mesh=ex.mesh, check_vma=False
        )(body, in_specs=(specs_v, specs_v), out_specs=(specs_v, specs_v))

    def timed(name, stage_fn):
        smf = make_sm(stage_fn)

        @jax.jit
        def loop(a, b):
            def sbody(carry, _):
                return smf(*carry), ()

            (r, i), _ = jax.lax.scan(sbody, (a, b), None, length=REPS)
            return r.ravel()[0] + i.ravel()[0]

        try:
            float(jax.device_get(loop(vre, vim)))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = loop(vre, vim)
                float(jax.device_get(out))
                best = min(best, (time.perf_counter() - t0) / REPS)
            record({"name": name, "ms": round(best * 1e3, 3)})
            return best
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})
            return None

    # ---- cumulative prefixes of the real backward body ----
    def s_decompress(a, b, a_me, b_me, s_me):
        return jax.lax.switch(
            jnp.asarray(ex._branch_of_shard)[s_me],
            ex._decompress_branches,
            a.astype(rt), b.astype(rt),
        )

    def s_z(a, b, a_me, b_me, s_me):
        sre, sim = s_decompress(a, b, a_me, b_me, s_me)
        return offt.complex_matmul(sre, sim, *ex._wz_b, "sz,zk->sk", prec)

    def s_phase(a, b, a_me, b_me, s_me):
        sre, sim = s_z(a, b, a_me, b_me, s_me)
        if ex._align_rep is not None:
            cos_t, sin_t = lanecopy.phase_rep_tables_at(ex._align_rep, s_me, rt)
            sre, sim = lanecopy.apply_alignment_phase(sre, sim, cos_t, sin_t, -1)
        return sre, sim

    def s_packa(a, b, a_me, b_me, s_me):
        sre, sim = s_phase(a, b, a_me, b_me, s_me)
        return ex._pack_a(sre, s_me), ex._pack_a(sim, s_me)

    def s_unpacka(a, b, a_me, b_me, s_me):
        bre, bim = s_packa(a, b, a_me, b_me, s_me)
        return ex._unpack_a(bre, a_me), ex._unpack_a(bim, a_me)

    def s_y(a, b, a_me, b_me, s_me):
        gre, gim = s_unpacka(a, b, a_me, b_me, s_me)
        return offt.complex_matmul(gre, gim, *ex._wy_b, "yal,yk->kal", prec)

    def s_x(a, b, a_me, b_me, s_me):
        gre, gim = s_y(a, b, a_me, b_me, s_me)
        bre, bim = ex._pack_b(gre), ex._pack_b(gim)
        hre = bre.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, Lz)
        him = bim.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, Lz)
        return offt.complex_matmul(hre, him, *ex._wx_b, "ycl,cx->lyx", prec)

    timed("sm_decompress", s_decompress)
    timed("sm_+z", s_z)
    timed("sm_+phase", s_phase)
    timed("sm_+packA", s_packa)
    timed("sm_+unpackA", s_unpacka)
    timed("sm_+y", s_y)
    timed("sm_full_bwd_compute", s_x)

    # ---- the full real backward_impl under its own jit (no forward) ----
    @jax.jit
    def bwd_loop(a, b):
        def sbody(carry, _):
            out = ex._backward_sm(carry[0], carry[1], ex._value_indices)
            oa = out[0].ravel()[: carry[0].shape[1]][None].astype(rt)
            ob = out[1].ravel()[: carry[1].shape[1]][None].astype(rt)
            return (oa, ob), ()

        (r, i), _ = jax.lax.scan(sbody, (a, b), None, length=REPS)
        return r.ravel()[0] + i.ravel()[0]

    try:
        float(jax.device_get(bwd_loop(vre, vim)))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            out = bwd_loop(vre, vim)
            float(jax.device_get(out))
            best = min(best, (time.perf_counter() - t0) / REPS)
        record({"name": "sm_real_backward_impl", "ms": round(best * 1e3, 3)})
    except Exception as e:
        record({"name": "sm_real_backward_impl", "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
