"""Round-4 on-chip batch 2 — follow-ups to round4_measurements.py.

1. 512^3 blocked sparse-y re-run: batch 1's arm died because the ~800 MB of
   bucket matrices were embedded HLO constants; they are jit operands now.
2. 256^3 default re-pin after the operand restructure.
3. distributed multi-transform arms (batch 1 hit a mid-run source edit).
4. f64 512^3 host-facing split: device-side compute chain vs host-facing
   pair isolates staging from f64-emulation compute.

Appends to bench_results/round4_onchip2.json.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round4_onchip2.json"
)


def flops_pair(dim):
    import numpy as np

    n = dim**3
    return 2 * 5.0 * n * np.log2(n)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    args = ap.parse_args()

    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round4_measurements2", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import os

    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )
    from spfft_tpu.parameters import distribute_triplets

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def time_chain(ex, re0, im0, chain):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                sre, sim = ex.trace_backward(*carry, phase=ph)
                return (
                    ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph),
                    None,
                )

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, wim = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, cim = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    def measure_local(name, dim, sparsity, chain, env=None):
        saved = {k: os.environ.get(k) for k in (env or {})}
        os.environ.update({k: v for k, v in (env or {}).items() if v is not None})
        for k, v in (env or {}).items():
            if v is None:
                os.environ.pop(k, None)
        try:
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
            t = Transform(
                ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
                indices=trip, dtype=np.float32, engine="mxu",
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            n = len(trip)
            re0 = ex.put(rng.standard_normal(n).astype(np.float32))
            im0 = ex.put(rng.standard_normal(n).astype(np.float32))
            best, err = time_chain(ex, re0, im0, chain)
            record({
                "name": name, "dim": dim, "chain": chain,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
                "blocked_buckets": len(
                    getattr(ex, "_sparse_y_blocked", None) or ()
                ),
                "n_operands": len(getattr(ex, "phase_operands", ())),
            })
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    CH = 48 if args.quick else 384
    CH512 = 8 if args.quick else 48

    # 1 + 2
    measure_local("c2c_256_s15_r4b_default", 256, 0.659, CH)
    measure_local("c2c_512_sph15_r4b_default", 512, 0.659, CH512)
    measure_local(
        "c2c_512_sph15_r4b_g8", 512, 0.659, CH512,
        env={"SPFFT_TPU_SPARSE_Y_BLOCKS": "8"},
    )

    # 3: distributed multi-transform (-m 4 --shards 1)
    def measure_dist_multi(name, m, dim, sparsity, chain):
        try:
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, sparsity)
            per = distribute_triplets(trip, 1, dim)
            mesh = sp.make_fft_mesh(1)
            ts = [
                DistributedTransform(
                    ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim,
                    per, mesh=mesh, dtype=np.float32, engine="mxu",
                )
                for _ in range(m)
            ]
            exs = [t._exec for t in ts]
            rng = np.random.default_rng(0)
            vals = [
                (rng.standard_normal(len(p)) + 1j * rng.standard_normal(len(p)))
                .astype(np.complex64)
                for p in per
            ]
            pairs = [ex.pad_values(vals) for ex in exs]

            def body(carry, _):
                outs = []
                for ex, (re, im) in zip(exs, carry):
                    s = ex.trace_backward(re, im)
                    outs.append(ex.trace_forward(*s, ScalingType.FULL))
                return tuple(outs), None

            step = jax.jit(
                lambda ps: jax.lax.scan(body, ps, None, length=chain)[0]
            )
            out = step(tuple(pairs))
            float(jax.device_get(out[0][0].ravel()[0]))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = step(tuple(pairs))
                float(jax.device_get(out[0][0].ravel()[0]))
                best = min(best, (time.perf_counter() - t0) / (chain * m))
            record({
                "name": name, "m": m, "dim": dim, "chain": chain,
                "ms_per_transform_pair": round(best * 1e3, 3),
                "gflops_per_transform": round(flops_pair(dim) / best / 1e9, 1),
            })
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})

    CHM = 12 if args.quick else 96
    measure_dist_multi("dist1_m1_128_sph15", 1, 128, 0.659, CHM)
    measure_dist_multi("dist1_m4_128_sph15", 4, 128, 0.659, CHM)

    # 4: f64 512^3 R2C — device-side compute chain vs host-facing pair
    def run_f64():
        jax.config.update("jax_enable_x64", True)
        try:
            dim = 128 if args.quick else 512
            trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
            trip = trip[trip[:, 0] >= 0]
            t = Transform(
                ProcessingUnit.GPU, TransformType.R2C, dim, dim, dim,
                indices=trip, dtype=np.float64,
            )
            ex = t._exec
            rng = np.random.default_rng(0)
            n = len(trip)
            re0 = ex.put(rng.standard_normal(n))
            im0 = ex.put(rng.standard_normal(n))
            phase = getattr(ex, "phase_operands", ())

            # device-side compute: CHAIN dependent pairs, no host staging
            def chain_fn(r, i, ph):
                def body(carry, _):
                    space = ex.trace_backward(*carry, phase=ph)
                    vr, vi = ex.trace_forward(
                        space, None, ScalingType.FULL, phase=ph
                    )
                    return (vr, vi), None

                return jax.lax.scan(body, (r, i), None, length=3)[0]

            step = jax.jit(chain_fn)
            wr, wi = step(re0, im0, phase)
            float(jax.device_get(wr.ravel()[0]))
            t0 = time.perf_counter()
            wr, wi = step(re0, im0, phase)
            float(jax.device_get(wr.ravel()[0]))
            compute_s = (time.perf_counter() - t0) / 3
            record({
                "name": "f64_512_r2c_device_compute",
                "s_per_pair": round(compute_s, 1),
            })

            # host-facing pair (staging + compute)
            v = rng.standard_normal(n) + 1j * rng.standard_normal(n)
            t.backward(v)
            t.forward(scaling=ScalingType.FULL)
            t0 = time.perf_counter()
            space = t.backward(v)
            t.forward(space, scaling=ScalingType.FULL)
            record({
                "name": "f64_512_r2c_hostfacing_b2",
                "s_per_pair": round(time.perf_counter() - t0, 1),
                "stage_chunk_mb": os.environ.get(
                    "SPFFT_TPU_STAGE_CHUNK_MB", "256(default)"
                ),
            })
        finally:
            jax.config.update("jax_enable_x64", False)

    try:
        run_f64()
    except Exception as e:
        record({"name": "f64_512_r2c_b2", "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
