"""Round-5 pencil stage bisection — where do 980 ms/pair go at 1x1/256^3?

The row-granular rewrite (z-minor layout, whole-row gathers) left the 1x1-mesh
pencil at 980 ms/pair on chip vs 5.5 ms local — the element-scatter theory is
dead (guard test holds, roundtrip 9e-6); this isolates the cost. Methodology =
microbench_ablate's: DEPENDENT chains inside one lax.scan, each variant mapping
a stick-pair to a stick-pair (stage outputs folded back by cheap reshapes/
slices), timed under PLAIN jit with shard indices passed as ints (the helpers
take s_me as an argument) — plus the full pipeline under the real 1x1
shard_map for the jit-vs-shard_map split.

Appends to bench_results/round5_pencil_bisect.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_pencil_bisect.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round5_pencil_bisect", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900, exit_code=2
    )
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import spfft_tpu as sp
    from spfft_tpu import DistributedTransform, ProcessingUnit, TransformType
    from spfft_tpu.ops import fft as offt

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    dim = 256
    trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
    t = DistributedTransform(
        ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, trip,
        mesh=sp.make_fft_mesh2(1, 1), dtype=np.float32, engine="mxu",
    )
    ex = t._exec
    p = ex.params
    rt = ex.real_dtype
    S, Z, Y = ex._S, p.dim_z, p.dim_y
    Ax, Lz, Ly, P1, P2 = ex._Ax, ex._Lz, ex._Ly, ex.P1, ex.P2
    SG = ex._SG
    prec = ex._precision
    record({
        "name": "plan_geometry", "S": int(S), "Z": int(Z), "Y": int(Y),
        "Ax": int(Ax), "Lz": int(Lz), "Ly": int(Ly), "SG": int(SG),
        "engine": t._engine,
    })
    rng = np.random.default_rng(0)
    spair = tuple(
        jnp.asarray(rng.standard_normal((S, Z)).astype(rt)) for _ in range(2)
    )

    REPS = 48

    def timed(name, fn, x0=spair):
        """Dependent-chain time of fn: pair -> same-shape pair."""
        @jax.jit
        def loop(a, b):
            def body(carry, _):
                return fn(*carry), ()

            (r, i), _ = jax.lax.scan(body, (a, b), None, length=REPS)
            return r.ravel()[0] + i.ravel()[0]

        try:
            float(loop(*x0))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                float(loop(*x0))
                best = min(best, (time.perf_counter() - t0) / REPS)
            record({"name": name, "ms": round(best * 1e3, 3)})
            return best
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})
            return None

    def fold_to_sticks(x):
        """Any array -> (S, Z) by flatten/slice/pad (cheap, fusible)."""
        flat = x.ravel()
        n = S * Z
        if flat.shape[0] >= n:
            return flat[:n].reshape(S, Z)
        return jnp.pad(flat, (0, n - flat.shape[0])).reshape(S, Z)

    # ---- cumulative pipeline prefixes, stick-pair -> stick-pair ----
    def v_z(a, b):
        return offt.complex_matmul(a, b, *ex._wz_b, "sz,zk->sk", prec)

    def v_packa(a, b):
        a, b = v_z(a, b)
        return fold_to_sticks(ex._pack_a(a, 0)), fold_to_sticks(ex._pack_a(b, 0))

    def v_unpacka(a, b):
        a, b = v_z(a, b)
        ba, bb = ex._pack_a(a, 0), ex._pack_a(b, 0)
        return (
            fold_to_sticks(ex._unpack_a(ba, 0)),
            fold_to_sticks(ex._unpack_a(bb, 0)),
        )

    def v_y(a, b):
        a, b = v_z(a, b)
        ga = ex._unpack_a(ex._pack_a(a, 0), 0)
        gb = ex._unpack_a(ex._pack_a(b, 0), 0)
        ga, gb = offt.complex_matmul(ga, gb, *ex._wy_b, "yal,yk->kal", prec)
        return fold_to_sticks(ga), fold_to_sticks(gb)

    def v_packb(a, b):
        a, b = v_z(a, b)
        ga = ex._unpack_a(ex._pack_a(a, 0), 0)
        gb = ex._unpack_a(ex._pack_a(b, 0), 0)
        ga, gb = offt.complex_matmul(ga, gb, *ex._wy_b, "yal,yk->kal", prec)
        ba, bb = ex._pack_b(ga), ex._pack_b(gb)
        ha = ba.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, Lz)
        hb = bb.transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, Lz)
        return fold_to_sticks(ha), fold_to_sticks(hb)

    def v_x(a, b):
        a, b = v_z(a, b)
        ga = ex._unpack_a(ex._pack_a(a, 0), 0)
        gb = ex._unpack_a(ex._pack_a(b, 0), 0)
        ga, gb = offt.complex_matmul(ga, gb, *ex._wy_b, "yal,yk->kal", prec)
        ha = ex._pack_b(ga).transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, Lz)
        hb = ex._pack_b(gb).transpose(1, 0, 2, 3).reshape(Ly, P1 * Ax, Lz)
        oa, ob = offt.complex_matmul(ha, hb, *ex._wx_b, "ycl,cx->lyx", prec)
        return fold_to_sticks(oa), fold_to_sticks(ob)

    timed("z_only", v_z)
    timed("z+packA", v_packa)
    timed("z+packA+unpackA", v_unpacka)
    timed("z+..+y", v_y)
    timed("z+..+packB", v_packb)
    timed("z+..+x (full bwd compute)", v_x)

    # ---- standalone suspects ----
    grid_pair = tuple(
        jnp.asarray(rng.standard_normal((Y, Ax, Lz)).astype(rt))
        for _ in range(2)
    )

    def y_only(a, b):
        return offt.complex_matmul(a, b, *ex._wy_b, "yal,yk->kal", prec)

    timed("y_matmul_alone", y_only, grid_pair)

    h_pair = tuple(
        jnp.asarray(rng.standard_normal((Ly, P1 * Ax, Lz)).astype(rt))
        for _ in range(2)
    )

    # fold: (Lz, Ly, X) -> (Ly, C, Lz) shape for the chain
    def x_only2(a, b):
        oa, ob = offt.complex_matmul(a, b, *ex._wx_b, "ycl,cx->lyx", prec)
        fa = oa.ravel()[: Ly * P1 * Ax * Lz].reshape(Ly, P1 * Ax, Lz)
        fb = ob.ravel()[: Ly * P1 * Ax * Lz].reshape(Ly, P1 * Ax, Lz)
        return fa, fb

    timed("x_matmul_alone", x_only2, h_pair)

    def x_natural(a, b):
        oa, ob = offt.complex_matmul(a, b, *ex._wx_b, "ycl,cx->yxl", prec)
        fa = oa.ravel()[: Ly * P1 * Ax * Lz].reshape(Ly, P1 * Ax, Lz)
        fb = ob.ravel()[: Ly * P1 * Ax * Lz].reshape(Ly, P1 * Ax, Lz)
        return fa, fb

    timed("x_matmul_natural_order", x_natural, h_pair)

    # ---- full pipeline under the real 1x1 shard_map (reference point) ----
    from spfft_tpu import ScalingType

    vals = (
        rng.standard_normal(t.num_local_elements(0))
        + 1j * rng.standard_normal(t.num_local_elements(0))
    ).astype(np.complex64)
    pairs = ex.pad_values([vals])
    phase = getattr(ex, "phase_operands", ())

    def chain_fn(r, i, ph):
        def body(carry, _):
            sre, sim = ex.trace_backward(*carry, phase=ph)
            return ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph), None

        return jax.lax.scan(body, (r, i), None, length=REPS)[0]

    try:
        step = jax.jit(chain_fn)
        wre, _ = step(pairs[0], pairs[1], phase)
        float(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, _ = step(pairs[0], pairs[1], phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / REPS)
        record({"name": "full_pair_shardmap_1x1", "ms": round(best * 1e3, 3)})
    except Exception as e:
        record({"name": "full_pair_shardmap_1x1", "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
