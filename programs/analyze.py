"""Static-analysis CLI: run the ``spfft_tpu.analysis`` checkers as a gate.

The one command CI (``./ci.sh analyze``) and developers run:

    python programs/analyze.py                 # full gate, human output
    python programs/analyze.py --json -        # spfft_tpu.analysis/1 report
    python programs/analyze.py --only SA011    # one checker (code or name)
    python programs/analyze.py --write-baseline  # accept current findings
    python programs/analyze.py --list          # the checker catalog
    python programs/analyze.py --list-noqa     # suppression audit (orphans exit 3)
    python programs/analyze.py --lockdep-check R.json  # runtime-vs-static graph

Exit status: 0 green (every finding baselined, no stale baseline entries),
3 when the gate trips — a NEW finding, a STALE baseline entry (a fixed
finding must leave the baseline, or the baseline rots into a blanket
waiver), an ORPHANED ``# noqa`` suppression under ``--list-noqa``, or an
unexplained runtime lock edge under ``--lockdep-check`` — and 2 on usage
errors. The distinct exit 3 is the same convention as
``programs/perf_gate.py``: a tripped gate, not a crashed tool.

Checkers run on a thread pool by default (``--jobs``, pure functions of
the parsed tree; ``--jobs 1`` for the serial reference — the test suite
asserts identical findings both ways).

The analysis package is loaded standalone (no ``spfft_tpu`` import, no
``jax``) — the same import-free rule the old ``programs/lint.py`` followed,
so the gate runs in milliseconds on hosts with no accelerator stack warmed.
"""
from __future__ import annotations

import argparse
import importlib.util
import json
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent

_PKG_NAME = "spfft_tpu_analysis_standalone"


def load_analysis(root: Path = ROOT):
    """Load ``spfft_tpu/analysis`` as a standalone package (relative
    imports intact, ``spfft_tpu/__init__`` — and therefore jax — never
    executed)."""
    if _PKG_NAME in sys.modules:
        return sys.modules[_PKG_NAME]
    pkg_dir = root / "spfft_tpu" / "analysis"
    spec = importlib.util.spec_from_file_location(
        _PKG_NAME,
        pkg_dir / "__init__.py",
        submodule_search_locations=[str(pkg_dir)],
    )
    mod = importlib.util.module_from_spec(spec)
    sys.modules[_PKG_NAME] = mod
    try:
        spec.loader.exec_module(mod)
    except BaseException:
        del sys.modules[_PKG_NAME]
        raise
    return mod


def _ran_codes(analysis, only) -> set:
    """Codes of the checkers a ``--only`` selection actually runs."""
    wanted = set(only)
    return {
        c.code for c in analysis.CHECKERS.values()
        if c.name in wanted or c.code in wanted
    }


# The import-hygiene checkers honor the legacy "any noqa on the line"
# contract INSIDE the checker, so a raw run cannot distinguish their live
# suppressions from orphans — the audit counts them live.
SELF_EXEMPT_CODES = ("SA001", "SA002")


def run_list_noqa(analysis, *, root: Path, quiet=False) -> int:
    """The suppression audit: every in-tree ``# noqa: SA*`` with its
    checker doc, ORPHANED ones (the code no longer fires on that line)
    exit 3 — a dead suppression hides the next real regression there."""
    tree = analysis.Tree(root=root)
    suppressions = analysis.list_noqa(tree)
    raw = analysis.run(tree, suppress=False)
    fired = {(f.code, f.file, f.line) for f in raw}
    by_code = {c.code: c for c in analysis.CHECKERS.values()}
    orphans = 0
    for row in suppressions:
        for code in row["codes"]:
            entry = by_code.get(code)
            live = (
                code in SELF_EXEMPT_CODES
                or (code, row["file"], row["line"]) in fired
            )
            status = "live" if live else "ORPHANED"
            if not live:
                orphans += 1
            if not quiet or not live:
                name = entry.name if entry else "unknown checker"
                print(f"{row['file']}:{row['line']}: {code} ({name}) — {status}")
                if entry and not live:
                    print(f"    {entry.doc}")
    if orphans:
        print(
            f"noqa audit TRIPPED: {orphans} orphaned suppression(s) — the "
            "code no longer fires there; delete the noqa (or it will hide "
            "the next real finding on that line)"
        )
        return 3
    if not quiet:
        print(f"noqa audit ok: {len(suppressions)} suppression(s), all live")
    return 0


def run_lockdep_check(analysis, *, root: Path, report_paths) -> int:
    """Cross-check runtime lockdep report(s) against the SA011 static
    graph: unexplained runtime edges (the static model is stale), observed
    cycles, and blocking waits exit 3. Multiple reports (one per worker
    host of a multi-host run) merge into one site-keyed graph first
    (:func:`spfft_tpu.analysis.lockdep.merge_reports`)."""
    if isinstance(report_paths, (str, Path)):
        report_paths = [report_paths]
    docs = []
    for report_path in report_paths:
        try:
            one = json.loads(Path(report_path).read_text())
        except OSError as e:
            print(f"cannot read lockdep report: {e}", file=sys.stderr)
            return 2
        except json.JSONDecodeError as e:
            print(
                f"malformed lockdep report {report_path}: {e}",
                file=sys.stderr,
            )
            return 2
        missing = analysis.lockdep.validate_report(one)
        if missing:
            print(
                f"lockdep report {report_path} schema incomplete: {missing}",
                file=sys.stderr,
            )
            return 2
        docs.append(one)
    doc = docs[0] if len(docs) == 1 else analysis.lockdep.merge_reports(docs)
    static = analysis.locks.static_graph(analysis.Tree(root=root))
    chk = analysis.lockdep.crosscheck(doc, static)
    for f in chk["findings"]:
        print(f"{f['where']}: [lockdep:{f['kind']}] {f['message']}")
    n_static = len(chk["explained"]["static"])
    n_dynamic = len(chk["explained"]["dynamic"])
    print(
        f"lockdep cross-check ({len(docs)} report(s)): "
        f"{doc['counts']['locks']} lock(s), "
        f"{doc['counts']['edges']} edge(s) — {n_static} matched the static "
        f"graph, {n_dynamic} on dynamic (statically untracked) locks, "
        f"{len(chk['findings'])} finding(s)"
    )
    return 3 if chk["findings"] else 0


def run_gate(
    analysis,
    *,
    root: Path,
    baseline_path: Path,
    only=None,
    json_out=None,
    write_baseline=False,
    quiet=False,
    jobs=None,
) -> int:
    """The gate body (``programs/lint.py`` reuses it for checkers 1-9)."""
    tree = analysis.Tree(root=root)
    findings = analysis.run(tree, only=only, jobs=jobs)

    if write_baseline:
        doc = analysis.baseline_doc(findings)
        if only:
            # a subset write replaces only the ran checkers' entries — the
            # other checkers' accepted findings must survive the rewrite
            ran = _ran_codes(analysis, only)
            kept = {
                k for k in analysis.load_baseline(baseline_path)
                if k.split(":", 1)[0] not in ran
            }
            doc["entries"] = sorted(set(doc["entries"]) | kept)
        baseline_path.write_text(json.dumps(doc, indent=2) + "\n")
        print(
            f"wrote {baseline_path} ({len(doc['entries'])} accepted "
            f"finding(s))"
        )
        return 0

    accepted = analysis.load_baseline(baseline_path)
    if only:
        # a subset run must not call the other checkers' baseline entries
        # stale: restrict staleness to the codes that actually ran
        accepted = {
            k for k in accepted
            if k.split(":", 1)[0] in _ran_codes(analysis, only)
        }
    split = analysis.apply_baseline(findings, accepted)

    if json_out is not None:
        doc = analysis.report_doc(
            findings, split, root=str(root), baseline_path=str(baseline_path)
        )
        text = json.dumps(doc, indent=2) + "\n"
        if json_out == "-":
            sys.stdout.write(text)
        else:
            Path(json_out).write_text(text)

    if not quiet and json_out != "-":
        for f in split["new"]:
            print(f.render())
        if split["baselined"]:
            print(
                f"{len(split['baselined'])} baselined finding(s) "
                f"(accepted in {baseline_path.name})"
            )
        for key in split["stale"]:
            print(
                f"stale baseline entry (the finding was fixed — remove it "
                f"or rerun --write-baseline): {key}"
            )
    if split["new"] or split["stale"]:
        if not quiet and json_out != "-":
            print(
                f"analysis gate TRIPPED: {len(split['new'])} new finding(s), "
                f"{len(split['stale'])} stale baseline entr(ies)"
            )
        return 3
    if not quiet and json_out != "-":
        names = only or list(analysis.CHECKERS)
        print(
            f"analysis ok: {len(names)} checker(s), "
            f"{len(findings)} finding(s), all baselined"
        )
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--json", metavar="PATH",
        help="write the spfft_tpu.analysis/1 JSON report (- for stdout)",
    )
    p.add_argument(
        "--write-baseline", action="store_true",
        help="accept every current finding into the baseline file and exit 0",
    )
    p.add_argument(
        "--only", action="append", metavar="CHECKER",
        help="run one checker (code SA0NN or slug name); repeatable",
    )
    p.add_argument(
        "--root", default=str(ROOT), metavar="DIR",
        help="tree to analyze (default: this checkout)",
    )
    p.add_argument(
        "--baseline", metavar="PATH",
        help="baseline file (default: <root>/analysis_baseline.json)",
    )
    p.add_argument(
        "--list", action="store_true", help="print the checker catalog"
    )
    p.add_argument(
        "--list-noqa", action="store_true",
        help="audit every in-tree `# noqa: SA*` suppression; orphaned "
        "suppressions (the code no longer fires on that line) exit 3",
    )
    p.add_argument(
        "--lockdep-check", metavar="REPORT", nargs="+",
        help="cross-check runtime lockdep report(s) "
        "(spfft_tpu.analysis.lockdep/1 JSON) against the SA011 static "
        "graph; multiple reports — e.g. one per worker host of a "
        "multi-host run — are merged (lockdep.merge_reports) and checked "
        "as one graph; unexplained edges/cycles/blocking exit 3",
    )
    p.add_argument(
        "--jobs", type=int, default=None, metavar="N",
        help="checker thread-pool width (default: one per CPU, capped 8; "
        "1 = serial)",
    )
    p.add_argument("-q", "--quiet", action="store_true")
    args = p.parse_args(argv)

    root = Path(args.root).resolve()
    analysis = load_analysis(root if (root / "spfft_tpu" / "analysis").is_dir() else ROOT)

    if args.list:
        for entry in analysis.CHECKERS.values():
            print(f"{entry.code}  {entry.severity:5s}  {entry.name}")
        return 0

    try:
        if args.list_noqa:
            return run_list_noqa(analysis, root=root, quiet=args.quiet)
        if args.lockdep_check:
            return run_lockdep_check(
                analysis, root=root, report_paths=args.lockdep_check
            )
        jobs = args.jobs
        if jobs is None:
            import os

            jobs = min(8, os.cpu_count() or 1)
        baseline_path = Path(
            args.baseline if args.baseline else root / "analysis_baseline.json"
        )
        return run_gate(
            analysis,
            root=root,
            baseline_path=baseline_path,
            only=args.only,
            json_out=args.json,
            write_baseline=args.write_baseline,
            quiet=args.quiet,
            jobs=jobs,
        )
    except analysis.AnalysisError as e:
        print(f"analysis error: {e}", file=sys.stderr)
        return 2


if __name__ == "__main__":
    sys.exit(main())
