"""Build a plan, print its plan card + a run-metrics snapshot, write JSON.

The observability CLI (spfft_tpu.obs): the card records every plan-time
decision — geometry, sparsity, engine choices, and for distributed plans the
exchange discipline's wire bytes / rounds / transport plus the cost-model
table of the alternatives the DEFAULT policy weighed — and the snapshot
records what one roundtrip actually did (transforms executed, bytes staged,
dispatch/wait latencies). The emitted JSON is schema-validated
(obs.validate_report) before it is written; a missing key exits nonzero, so
ci.sh catches plan-card drift without TPU hardware.

Usage:
    python programs/report.py -d 32 32 32                       # local plan
    python programs/report.py -d 64 64 64 --shards 4 --engine mxu
    python programs/report.py -d 64 64 64 --pencil 2 2 -o card.json
    python programs/report.py -d 32 32 32 --no-compiled         # skip compile
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


def build_plan(args):
    import spfft_tpu as sp
    from spfft_tpu import ExchangeType, ProcessingUnit, TransformType

    dx, dy, dz = args.d
    radius = sp.spherical_radius_for_fraction(args.s)
    trip = sp.create_spherical_cutoff_triplets(
        dx, dy, dz, min(radius, 1.0), hermitian_symmetry=args.r2c
    )
    ttype = TransformType.R2C if args.r2c else TransformType.C2C
    if args.pencil:
        from spfft_tpu.parallel import make_fft_mesh2

        mesh = make_fft_mesh2(*args.pencil)
        return sp.DistributedTransform(
            ProcessingUnit.HOST, ttype, dx, dy, dz, trip, mesh=mesh,
            engine=args.engine, exchange_type=ExchangeType[args.exchange],
        )
    if args.shards > 1:
        from spfft_tpu.parallel import make_fft_mesh

        mesh = make_fft_mesh(args.shards)
        return sp.DistributedTransform(
            ProcessingUnit.HOST, ttype, dx, dy, dz, trip, mesh=mesh,
            engine=args.engine, exchange_type=ExchangeType[args.exchange],
        )
    return sp.Transform(
        ProcessingUnit.HOST, ttype, dx, dy, dz, indices=trip,
        engine=args.engine,
    )


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("-d", nargs=3, type=int, default=[32, 32, 32],
                    metavar=("X", "Y", "Z"))
    ap.add_argument("-s", type=float, default=0.15, help="nonzero fraction")
    ap.add_argument("--r2c", action="store_true", help="R2C instead of C2C")
    ap.add_argument("--engine", default="auto", choices=["auto", "xla", "mxu"])
    ap.add_argument("--shards", type=int, default=1,
                    help="1-D slab mesh width (1 = local plan)")
    ap.add_argument("--pencil", nargs=2, type=int, metavar=("P1", "P2"),
                    help="2-D pencil mesh (overrides --shards)")
    ap.add_argument("--exchange", default="DEFAULT",
                    help="exchange discipline name (distributed plans)")
    ap.add_argument("--no-compiled", action="store_true",
                    help="skip compiled-program stats (compile can dominate)")
    ap.add_argument("--no-roundtrip", action="store_true",
                    help="emit the card without executing a transform pair")
    ap.add_argument("-o", default=None, help="write the report JSON here")
    args = ap.parse_args(argv)

    # mesh-width CPU devices must exist before the first backend touch
    shards = args.pencil[0] * args.pencil[1] if args.pencil else args.shards
    if shards > 1:
        from spfft_tpu.parallel.mesh import ensure_virtual_devices

        ensure_virtual_devices(shards, warn=True, platform="cpu")

    from spfft_tpu import ScalingType, obs

    plan = build_plan(args)
    card = plan.report(include_compiled=not args.no_compiled)

    if not args.no_roundtrip:
        # one roundtrip so the snapshot carries real run counters
        rng = np.random.default_rng(0)
        if args.shards > 1 or args.pencil:
            values = [
                rng.standard_normal(plan.num_local_elements(r))
                + 1j * rng.standard_normal(plan.num_local_elements(r))
                for r in range(plan.num_shards)
            ]
        else:
            n = plan.num_local_elements
            values = rng.standard_normal(n) + 1j * rng.standard_normal(n)
        plan.backward(values)
        plan.forward(scaling=ScalingType.FULL)

    # run_id rides top-level too (it is also inside the card): the join key
    # against a flight-recorder snapshot/dump from the same process;
    # verify_mode stamps the verification setting so perf/metrics rows are
    # never compared across unlike verification settings
    report = {
        "plan": card,
        "metrics": obs.snapshot(),
        "run_id": card.get("run_id"),
        "verify_mode": card.get("verification", {}).get("mode", "off"),
    }
    missing = obs.validate_report(report)

    print(json.dumps(card, indent=2))
    print()
    print(obs.prometheus_text(report["metrics"]))
    if args.o:
        Path(args.o).write_text(json.dumps(report, indent=2) + "\n")
        print(f"report written to {args.o}")
    if missing:
        print(f"report schema INCOMPLETE, missing: {missing}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
