"""fleetstat: scrape, merge, validate and export fleet metrics.

The operator CLI of the fleet observability layer
(:mod:`spfft_tpu.obs.fleet`): scrapes each named host's ``obs.snapshot()``
over the ``metrics`` RPC op (one bounded ``SPFFT_TPU_FLEET_SCRAPE_S``
deadline per host — a dead host is stamped ``unreachable``, never a hung
scrape) and merges them into one host-labeled ``spfft_tpu.obs.fleet/1``
document, validated before it is written. ``--check`` re-validates an
existing document instead of scraping (the CI hook proving a doctored
document trips the schema pin), ``--prom`` renders the Prometheus
exposition text.

Exit status: 0 clean, 1 usage/scrape error (no host answered), 3 validation
findings (distinct, so CI can tell "schema tripped" from "tool broken" —
the ``perf_gate.py`` discipline).

Usage:
    python programs/fleetstat.py --host host0=127.0.0.1:4242 \
        --host host1=127.0.0.1:4243 -o fleet.json
    python programs/fleetstat.py --host host0=127.0.0.1:4242 --prom
    python programs/fleetstat.py --check fleet.json
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--host", action="append", default=[], metavar="NAME=ADDR:PORT",
        help="one worker host to scrape (repeatable)",
    )
    p.add_argument(
        "--check", default=None, metavar="FLEET_JSON",
        help="validate an existing fleet document instead of scraping",
    )
    p.add_argument(
        "--timeout-s", type=float, default=None,
        help="per-host scrape deadline (default SPFFT_TPU_FLEET_SCRAPE_S)",
    )
    p.add_argument(
        "--prom", action="store_true",
        help="print the Prometheus exposition text instead of JSON",
    )
    p.add_argument("-o", "--output", default=None, help="write JSON here")
    return p


def _parse_hosts(specs: list) -> list:
    """[(name, address)] from NAME=ADDR:PORT specs (typed on malformed)."""
    out = []
    for spec in specs:
        name, eq, address = spec.partition("=")
        if not eq or not name or not address:
            raise SystemExit(
                f"malformed --host {spec!r}: expected NAME=ADDR:PORT"
            )
        out.append((name, address))
    return out


def _report(doc: dict, findings: list) -> None:
    states = {
        h: entry.get("state") for h, entry in doc.get("hosts", {}).items()
    }
    print(
        f"fleet: {len(states)} hosts "
        f"({sum(1 for s in states.values() if s == 'live')} live), "
        f"{len(doc.get('counters', {}))} counters, "
        f"{len(doc.get('gauges', {}))} gauges, "
        f"{len(doc.get('histograms', {}))} histograms",
        file=sys.stderr,
    )
    for host, state in sorted(states.items()):
        if state != "live":
            err = doc["hosts"][host].get("error")
            print(f"  {host}: {state} ({err})", file=sys.stderr)
    for finding in findings:
        print(f"  INVALID: {finding}", file=sys.stderr)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    from spfft_tpu.obs import fleet

    if args.check:
        doc = json.loads(Path(args.check).read_text())
        findings = fleet.validate_fleet(doc)
        _report(doc if isinstance(doc, dict) else {}, findings)
        return 3 if findings else 0

    hosts = _parse_hosts(args.host)
    if not hosts:
        print("no hosts given (--host NAME=ADDR:PORT)", file=sys.stderr)
        return 1

    from spfft_tpu.serve.rpc import RpcClient

    class _Handle:
        lost = False

        def __init__(self, name, address):
            self.name = name
            self.client = RpcClient(address, timeout_s=args.timeout_s)

    handles = [_Handle(name, address) for name, address in hosts]
    try:
        doc = fleet.fleet_snapshot(handles, timeout_s=args.timeout_s)
    finally:
        for h in handles:
            h.client.close()
    findings = fleet.validate_fleet(doc)
    _report(doc, findings)
    if not any(
        entry.get("state") == "live" for entry in doc["hosts"].values()
    ):
        print("no host answered the scrape", file=sys.stderr)
        return 1
    if args.prom:
        out = fleet.fleet_prometheus_text(doc)
    else:
        out = json.dumps(doc, indent=1, sort_keys=True)
    if args.output:
        Path(args.output).write_text(out)
        print(f"wrote {args.output}", file=sys.stderr)
    else:
        print(out)
    return 3 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
