"""Autotune a plan and persist its wisdom — the planner CLI of
``spfft_tpu.tuning`` (the FFTW ``fftw-wisdom`` tool analogue).

Builds the requested plan under ``policy="tuned"``: wisdom-store hit returns
the remembered choice with zero trials; a miss measures every candidate
(exchange disciplines for distributed plans, the engine axis for local ones)
on the real geometry/mesh/dtype and records the winner in the store named by
``SPFFT_TPU_WISDOM`` (``--wisdom`` sets it for the run). The JSON report
carries the tuning record (provenance, hit/miss, per-candidate trial
timings), the resulting plan card, and the wisdom state — everything a later
benchmark needs to reproduce the decision.

On CPU-only hosts trials are skipped (the model policy answers) unless
``--allow-cpu-trials`` / ``SPFFT_TPU_TUNE_CPU=1`` — CPU collective timings
must never poison wisdom an accelerator plan would read; the override exists
for CI smoke and tests. ci.sh's ``tune`` stage runs this program twice on a
tiny grid with a tmp wisdom file and asserts the second run hits.

Usage:
    python programs/tune.py -d 64 64 64 --shards 4 -o tuned.json
    python programs/tune.py -d 32 32 32 --mesh2 2 2 --wisdom wisdom.json
    python programs/tune.py -d 32 32 32 --repeats 3      # local engine axis
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser(description="autotune a plan into wisdom")
    ap.add_argument("-d", nargs=3, type=int, default=None, metavar=("X", "Y", "Z"))
    ap.add_argument("-s", type=float, default=0.3, help="nonzero fraction")
    ap.add_argument("--r2c", action="store_true")
    ap.add_argument("--shards", type=int, default=1, help="1-D mesh size (1 = local)")
    ap.add_argument(
        "--mesh2", nargs=2, type=int, default=None, metavar=("P1", "P2"),
        help="2-D pencil mesh factors (overrides --shards)",
    )
    ap.add_argument("--engine", choices=["auto", "mxu", "xla"], default="auto")
    ap.add_argument("--dtype", choices=["float32", "float64"], default=None)
    ap.add_argument("--wisdom", default=None, help="wisdom file (sets SPFFT_TPU_WISDOM)")
    ap.add_argument("--repeats", type=int, default=None, help="timed repeats per trial")
    ap.add_argument("--warmup", type=int, default=None, help="warmup roundtrips per trial")
    ap.add_argument(
        "--allow-cpu-trials", action="store_true",
        help="run trials on CPU-only hosts (sets SPFFT_TPU_TUNE_CPU=1; CI/tests)",
    )
    ap.add_argument(
        "--export", default=None, metavar="BUNDLE",
        help="after tuning (or alone, without -d), export the active wisdom "
        "store as a fleet bundle at BUNDLE — a new host --merge'd from it "
        "(or pointed at it via SPFFT_TPU_WISDOM) warm-starts pre-tuned",
    )
    ap.add_argument(
        "--merge", default=None, metavar="BUNDLE",
        help="before tuning (or alone, without -d), merge the fleet bundle "
        "at BUNDLE into the active wisdom store (best-measured-wins on key "
        "conflict, version-checked, corrupt bundles quarantined)",
    )
    ap.add_argument("-o", default=None, help="output JSON path")
    args = ap.parse_args(argv)

    import os

    from spfft_tpu.tuning import (
        TUNE_CPU_ENV,
        TUNE_REPEATS_ENV,
        TUNE_WARMUP_ENV,
        WISDOM_ENV,
        wisdom_state,
    )

    if args.wisdom:
        os.environ[WISDOM_ENV] = args.wisdom
    if args.repeats is not None:
        os.environ[TUNE_REPEATS_ENV] = str(args.repeats)
    if args.warmup is not None:
        os.environ[TUNE_WARMUP_ENV] = str(args.warmup)
    if args.allow_cpu_trials:
        os.environ[TUNE_CPU_ENV] = "1"

    if args.d is None and not (args.export or args.merge):
        ap.error("-d is required unless --export/--merge runs bundle-only")
    from spfft_tpu.tuning import active_store

    if args.merge:
        from spfft_tpu.errors import InvalidParameterError

        try:
            added, replaced = active_store().merge(args.merge)
        except InvalidParameterError as e:
            print(f"tune: {e}", file=sys.stderr)
            return 1
        print(
            f"merged bundle {args.merge}: {added} added, {replaced} replaced "
            "(best-measured-wins)"
        )
    if args.d is None:
        if args.export:
            count = active_store().export(args.export)
            print(f"exported {count} wisdom entries to {args.export}")
        return 0

    if args.mesh2 is not None:
        args.shards = args.mesh2[0] * args.mesh2[1]
    if args.shards == 1 and args.engine != "auto":
        # the local tuner's candidate space IS the engine axis; pinning the
        # engine leaves nothing to tune (Transform only tunes engine="auto")
        ap.error("local tuning explores the engine axis; use --engine auto "
                 "(explicit engines apply to distributed exchange tuning only)")
    if args.shards > 1 and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # virtual CPU mesh bootstrap (same as discipline_compare.py)
        from spfft_tpu.parallel.mesh import configure_virtual_devices

        configure_virtual_devices(args.shards, warn=True)

    import numpy as np
    import spfft_tpu as sp
    from spfft_tpu import obs
    from spfft_tpu.types import ProcessingUnit, TransformType

    dx, dy, dz = args.d
    radius = sp.spherical_radius_for_fraction(args.s)
    trip = sp.create_spherical_cutoff_triplets(
        dx, dy, dz, min(radius, 1.0), hermitian_symmetry=args.r2c
    )
    ttype = TransformType.R2C if args.r2c else TransformType.C2C
    dtype = np.dtype(args.dtype) if args.dtype else None
    import jax

    pu = (
        ProcessingUnit.HOST
        if jax.devices()[0].platform == "cpu"
        else ProcessingUnit.GPU
    )
    if args.shards > 1:
        mesh = (
            sp.make_fft_mesh2(*args.mesh2)
            if args.mesh2 is not None
            else sp.make_fft_mesh(args.shards)
        )
        plan = sp.DistributedTransform(
            pu, ttype, dx, dy, dz, trip, mesh=mesh, dtype=dtype,
            engine=args.engine, policy="tuned",
        )
    else:
        plan = sp.Transform(
            pu, ttype, dx, dy, dz, indices=trip, dtype=dtype,
            engine=args.engine, policy="tuned",
        )

    rec = plan._tuning
    if rec is None:
        print("plan was not tuned (the TUNED policy did not engage)", file=sys.stderr)
        return 1
    print(
        f"tune: provenance={rec['provenance']} hit={rec['hit']} "
        f"choice={rec['choice']} ({rec['reason']})"
    )
    for row in rec["trials"]:
        model = (
            f"  model_cost={row['model_cost_bytes']:,}B"
            if "model_cost_bytes" in row
            else ""
        )
        if "ms" in row:
            print(f"  {row['label']:20s} {row['ms']:9.3f} ms{model}")
        else:  # isolated trial failure (runner.run_trials error row)
            print(f"  {row['label']:20s}    FAILED: {row.get('error', '?')}")
    if args.export:
        count = active_store().export(args.export)
        print(f"exported {count} wisdom entries to {args.export}")
    doc = {
        "tuning": rec,
        "wisdom": wisdom_state(plan),
        "plan": plan.report(),
    }
    missing = obs.validate_plan_card(doc["plan"])
    if missing:
        print(f"plan card schema incomplete: {missing}", file=sys.stderr)
        return 1
    if args.o:
        Path(args.o).write_text(json.dumps(doc, indent=2))
        print(f"wrote {args.o}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
