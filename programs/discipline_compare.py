"""Exchange-discipline comparison: BUFFERED vs COMPACT_BUFFERED vs UNBUFFERED.

Measures, per shard count P, each discipline's (a) off-shard wire bytes per
repartition (exact accounting from the plan geometry), (b) sequential
collective rounds, and (c) wall-clock per backward+forward pair — the
bytes-AND-latency picture the discipline choice actually trades off
(parallel/ragged.py LATENCY note). The reference offers the same three wire
disciplines but publishes no guidance numbers (reference:
include/spfft/types.h:33-62); this program produces them for a given plan.

On a virtual CPU mesh (default here) wall-clock is indicative only — CPU
"collectives" are memory copies, so the chain's extra rounds cost far less
than they do over ICI, and ragged-all-to-all falls back to the chain
transport. Run on a real pod slice for decision-grade timings.

Usage:
    python programs/discipline_compare.py [--shards 8 16 32] [--dim 64]
        [--sparsity 0.3] [--imbalance 0.0] [--repeats 20] [--json out.json]
        [--policy {default,tuned}]

``--imbalance w`` skews the per-shard stick weights linearly from 1 to 1+w,
exercising the regime where exact-counts disciplines win on bytes.

``--policy`` A/Bs the DEFAULT resolvers against the explicit disciplines: a
fourth row per shard count measures the plan a bare ``ExchangeType.DEFAULT``
produces under the selected policy — ``default`` (the analytic cost model,
parallel/policy.py) or ``tuned`` (the empirical autotuner, spfft_tpu.tuning;
CPU trials are auto-allowed here since this program measures on the virtual
CPU mesh anyway). The row records which discipline the policy resolved to and
its decision provenance, so model picks and wisdom picks can be compared
against the exhaustive sweep they should have matched.

``--matrix`` switches to the **scenario matrix** (the comparative-study
format of arxiv.org/pdf/2506.08653: a grid of measured cells, not one
headline number): the cross product of ``--matrix-dims`` x
``--matrix-sparsity`` (extremes by default) x ``--matrix-types`` (c2c/r2c) x
``--matrix-dtypes`` (f32/f64) x both wire disciplines (padded BUFFERED and
exact-counts UNBUFFERED) x the **overlap axis** (``--matrix-overlap``,
default ``1 tuned``: bulk-synchronous, plus one autotuner-resolved cell per
scenario where the TUNED policy picks the discipline AND the OVERLAPPED
chunk count), each cell measured with the shared fenced chained-roundtrip
discipline and emitted as a keyed ``spfft_tpu.obs.perf/1`` row (per-stage
attribution, GFLOP/s, exchange_fraction) — the same row format
``programs/dbench.py`` writes, so ``programs/perf_gate.py`` gates matrix
documents identically and a per-scenario overlap win or regression is an
ordinary gate row.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
# sibling programs (dbench) resolve even when this file is loaded by path
# (tests import it via importlib, where the script dir is not on sys.path)
sys.path.insert(0, str(Path(__file__).resolve().parent))


def run_matrix(args):
    """The scenario matrix (module docstring): dims x sparsity x c2c/r2c x
    dtype x both wire disciplines x the overlap axis, each cell a keyed perf
    row measured with the shared fenced chained-roundtrip discipline
    (``dbench.measure_row``), written as a gate-compatible
    ``spfft_tpu.obs.perf.scaling/1`` document.

    The overlap axis (``--matrix-overlap``, default ``1 tuned``): integer
    chunk counts measure the padded BUFFERED discipline under the OVERLAPPED
    pipeline (UNBUFFERED's ragged transport clamps the knob, so it only
    carries the ``1`` cell); the literal ``tuned`` adds one cell per
    scenario whose plan resolves ``ExchangeType.DEFAULT`` under
    ``policy="tuned"`` with the overlap knob left to the autotuner — its key
    records whatever discipline/chunk count the trials picked, so
    per-scenario overlap wins and regressions land as ordinary gate rows."""
    import os

    import jax
    import numpy as np
    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ExchangeType,
        ProcessingUnit,
        TransformType,
    )
    from spfft_tpu.obs import perf

    import dbench  # sibling program: one row/key format, one gate

    P = args.shards[0]
    if "f64" in args.matrix_dtypes and not jax.config.read("jax_enable_x64"):
        jax.config.update("jax_enable_x64", True)
    if "tuned" in args.matrix_overlap:
        # tuned cells measure on this same virtual CPU mesh, so CPU trials
        # cannot poison accelerator wisdom any more than the sweep does
        os.environ.setdefault("SPFFT_TPU_TUNE_CPU", "1")
    int_overlaps = sorted({int(o) for o in args.matrix_overlap if o != "tuned"})
    mesh = sp.make_fft_mesh(P)
    pu = ProcessingUnit.GPU if args.engine == "mxu" else ProcessingUnit.HOST
    rows = []
    for dim in args.matrix_dims:
        for sparsity in args.matrix_sparsity:
            for ttype in args.matrix_types:
                radius = sp.spherical_radius_for_fraction(sparsity)
                trip = sp.create_spherical_cutoff_triplets(
                    dim, dim, dim, min(radius, 1.0),
                    hermitian_symmetry=ttype == "r2c",
                )
                for dt in args.matrix_dtypes:
                    cells = [
                        ("UNBUFFERED", "default", 1)
                    ] + [("BUFFERED", "default", ov) for ov in int_overlaps]
                    if "tuned" in args.matrix_overlap:
                        cells.append(("DEFAULT", "tuned", None))
                    for disc, policy, overlap in cells:
                        t = DistributedTransform(
                            pu,
                            TransformType.R2C if ttype == "r2c"
                            else TransformType.C2C,
                            dim, dim, dim,
                            np.asarray(trip).copy(),
                            mesh=mesh,
                            dtype=np.float64 if dt == "f64" else np.float32,
                            engine=args.engine,
                            exchange_type=ExchangeType[disc],
                            policy=policy,
                            overlap=overlap,
                        )
                        row = dbench.measure_row(t, args, scaling="matrix")
                        rows.append(row)
                        label = disc if policy == "default" else "TUNED"
                        print(
                            f"{dim:4d}^3 nnz={row['nnz_fraction']:.3f} "
                            f"{ttype} {dt} {label:10s} "
                            f"ov={row['overlap_chunks']:2d} "
                            f"{row['seconds_per_pair'] * 1e3:9.3f} ms/pair "
                            f"{row['gflops']:8.2f} GFLOP/s "
                            f"exch {row['exchange_fraction'] * 100:5.1f}%"
                        )
                    if args.matrix_batch > 0:
                        rows.extend(
                            measure_batch_rows(
                                dim, ttype, dt, trip, args,
                                args.matrix_batch,
                            )
                        )
    doc = {
        "schema": perf.SCALING_SCHEMA,
        "config": vars(args),
        "platform": str(mesh.devices.flat[0].platform),
        "rows": rows,
    }
    missing = perf.validate_scaling_doc(doc)
    if args.json:
        Path(args.json).write_text(json.dumps(doc, indent=2) + "\n")
        print(f"wrote {len(rows)} matrix rows to {args.json}")
    if missing:
        print(f"matrix doc INCOMPLETE, missing: {missing}", file=sys.stderr)
        return 1
    return 0


def measure_batch_rows(dim, ttype, dt, trip, args, B) -> list:
    """Two extra gate rows per scenario: a batch of ``B`` independent local
    transforms of this geometry executing full backward+forward pairs (a)
    one-at-a-time (``batchB:serial``) and (b) through the task-graph
    scheduler (``batchB:sched`` — :mod:`spfft_tpu.sched`: windowed
    dispatch, completion-order finalize). Effective seconds-per-pair =
    batch wall / B, reported as an ordinary perf row, so the scheduler's
    batched-multi-transform win (or a regression in it) is a per-scenario
    gate cell like every other matrix cell."""
    import time

    import numpy as np
    from spfft_tpu import ProcessingUnit, ScalingType, Transform, TransformType
    from spfft_tpu import sched
    from spfft_tpu.obs import perf

    import dbench

    dtype = np.float64 if dt == "f64" else np.float32
    pu = ProcessingUnit.GPU if args.engine == "mxu" else ProcessingUnit.HOST
    plans = [
        Transform(
            pu,
            TransformType.R2C if ttype == "r2c" else TransformType.C2C,
            dim, dim, dim, indices=np.asarray(trip).copy(), dtype=dtype,
            engine=args.engine,
        )
        for _ in range(B)
    ]
    rng = np.random.default_rng(0)
    if ttype == "r2c":
        # hermitian-consistent inputs: derive per-plan spectra from real fields
        values = [
            p.forward(rng.standard_normal((dim, dim, dim))) for p in plans
        ]
    else:
        values = [
            rng.standard_normal(p.num_local_elements)
            + 1j * rng.standard_normal(p.num_local_elements)
            for p in plans
        ]

    def serial_pairs():
        t0 = time.perf_counter()
        for p, v in zip(plans, values):
            p.backward(v)
            p.forward(None, ScalingType.FULL)
        return time.perf_counter() - t0

    def sched_pairs():
        graph = sched.TaskGraph()
        for p, v in zip(plans, values):
            graph.add("backward", payload=v, transform=p)
            graph.add("forward", scaling=ScalingType.FULL, transform=p)
        t0 = time.perf_counter()
        report = sched.run_graph(graph, max_inflight=2 * B)
        wall = time.perf_counter() - t0
        bad = {
            t: o for t, o in report.outcomes.items() if o != "completed"
        }
        assert not bad, f"scheduled batch cell degraded: {bad}"
        return wall

    rows = []
    repeats = max(2, min(3, args.repeats))
    for mode, run in (("serial", serial_pairs), ("sched", sched_pairs)):
        run()  # warmup (compilation, scheduler pool)
        walls = sorted(run() for _ in range(repeats))
        best = walls[0]
        median = (walls[(len(walls) - 1) // 2] + walls[len(walls) // 2]) / 2.0
        row = perf.perf_report(plans[0], best / B, repeats=repeats)
        row["scaling"] = "matrix"
        row["seconds_noise"] = (median - best) / best if best else 0.0
        row["batch"] = int(B)
        row["batch_mode"] = mode
        row["key"] = f"{dbench.row_key(row, 'matrix')}:batch{B}:{mode}"
        rows.append(row)
        print(
            f"{dim:4d}^3 nnz={row['nnz_fraction']:.3f} {ttype} {dt} "
            f"BATCH{B}/{mode:6s} "
            f"{row['seconds_per_pair'] * 1e3:9.3f} ms/pair "
            f"{row['gflops']:8.2f} GFLOP/s"
        )
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, nargs="+", default=[8, 16, 32])
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--sparsity", type=float, default=0.3)
    ap.add_argument("--imbalance", type=float, default=0.0)
    ap.add_argument("--repeats", type=int, default=20)
    ap.add_argument("--engine", default="mxu", choices=["xla", "mxu"])
    ap.add_argument(
        "--policy", default="default", choices=["default", "tuned"],
        help="resolver measured for the extra DEFAULT row (see module doc)",
    )
    ap.add_argument("--matrix", action="store_true",
                    help="measure the scenario matrix instead of the "
                    "per-shard-count discipline sweep (see module doc)")
    ap.add_argument("--matrix-dims", type=int, nargs="+", default=[16, 32])
    ap.add_argument("--matrix-sparsity", type=float, nargs="+",
                    default=[0.05, 0.6], help="nnz-fraction extremes")
    ap.add_argument("--matrix-types", nargs="+", default=["c2c", "r2c"],
                    choices=["c2c", "r2c"])
    ap.add_argument("--matrix-dtypes", nargs="+", default=["f32", "f64"],
                    choices=["f32", "f64"])
    ap.add_argument("--matrix-batch", type=int, default=4,
                    help="batched multi-transform rows per scenario: a "
                    "batch of this many local plans measured one-at-a-time "
                    "vs through the task-graph scheduler (serial vs sched "
                    "cells; 0 disables)")
    ap.add_argument("--matrix-overlap", nargs="+", default=["1", "tuned"],
                    help="overlap axis of the matrix: integer OVERLAPPED "
                    "chunk counts for the padded discipline, plus the "
                    "literal 'tuned' for an autotuner-resolved cell per "
                    "scenario (see run_matrix)")
    ap.add_argument("--chain", type=int, default=2,
                    help="chained roundtrips per dispatch (matrix mode)")
    ap.add_argument("--warmup", type=int, default=1)
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)

    import jax

    max_p = max(args.shards)
    try:
        jax.config.update("jax_platforms", "cpu")
        # version-portable device-count knob (jax_num_cpu_devices or the
        # older XLA flag — parallel/mesh.configure_virtual_devices)
        from spfft_tpu.parallel.mesh import configure_virtual_devices

        configure_virtual_devices(max_p, warn=True)
    except Exception as e:
        print(f"late platform config ({e}); using visible devices", file=sys.stderr)

    import numpy as np
    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ExchangeType,
        ProcessingUnit,
        ScalingType,
        TransformType,
    )
    from spfft_tpu.parameters import distribute_triplets

    if args.matrix:
        return run_matrix(args)

    dim = args.dim
    rng = np.random.default_rng(0)
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, args.sparsity)
    values = (
        rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    ).astype(np.complex64)

    disciplines = [
        ("BUFFERED", ExchangeType.BUFFERED),
        ("COMPACT", ExchangeType.COMPACT_BUFFERED),
        ("UNBUFFERED", ExchangeType.UNBUFFERED),
        # the A/B row: what a bare DEFAULT resolves to under --policy
        (f"DEFAULT:{args.policy}", ExchangeType.DEFAULT),
    ]
    if args.policy == "tuned":
        # this program already measures on the (virtual CPU) mesh, so CPU
        # trials cannot poison accelerator wisdom any more than the sweep does
        import os

        os.environ.setdefault("SPFFT_TPU_TUNE_CPU", "1")
    rows = []
    for P in args.shards:
        weights = 1.0 + args.imbalance * np.arange(P) / max(1, P - 1)
        per_shard = distribute_triplets(triplets, P, dim, weights=weights)
        vps = []
        order = {tuple(t): i for i, t in enumerate(map(tuple, triplets))}
        for p in per_shard:
            idx = [order[tuple(t)] for t in map(tuple, p)]
            vps.append(values[idx])
        mesh = sp.make_fft_mesh(P)
        for name, exchange in disciplines:
            t = DistributedTransform(
                ProcessingUnit.GPU if args.engine == "mxu" else ProcessingUnit.HOST,
                TransformType.C2C,
                dim,
                dim,
                dim,
                [p.copy() for p in per_shard],
                mesh=mesh,
                dtype=np.float32,
                engine=args.engine,
                exchange_type=exchange,
                # only the DEFAULT row resolves through a policy; explicit
                # disciplines are never overridden by either resolver
                policy=args.policy,
            )
            ex = t._exec
            pair = ex.pad_values(vps)
            out = t.backward_pair(*pair)  # compile both directions
            back = t.forward_pair(scaling=ScalingType.FULL)
            jax.block_until_ready((out, back))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(args.repeats):
                    out = t.backward_pair(*pair)
                    back = t.forward_pair(scaling=ScalingType.FULL)
                jax.block_until_ready((out, back))
                best = min(best, (time.perf_counter() - t0) / args.repeats)
            transport = getattr(ex._ragged, "transport", None)
            rows.append(
                {
                    "P": P,
                    "discipline": name,
                    "wire_bytes": ex.exchange_wire_bytes(),
                    "rounds": ex.exchange_rounds(),
                    "transport": transport,
                    "ms_per_pair": round(best * 1e3, 3),
                }
            )
            r = rows[-1]
            if exchange == ExchangeType.DEFAULT:
                rec = t._tuning
                r["resolved"] = t.exchange_type.name
                r["provenance"] = rec["provenance"] if rec else "model"
                if rec:
                    r["wisdom_hit"] = rec["hit"]
            print(
                f"P={P:3d} {name:16s} bytes={r['wire_bytes']:>12,} "
                f"rounds={r['rounds']:3d} {r['ms_per_pair']:8.2f} ms/pair"
                + (f" (transport={transport})" if transport else "")
                + (
                    f" -> {r['resolved']} [{r['provenance']}]"
                    if "resolved" in r
                    else ""
                )
            )
    if args.json:
        Path(args.json).write_text(json.dumps(
            {"config": vars(args), "rows": rows}, indent=2))
        print(f"wrote {args.json}")
    return rows


if __name__ == "__main__":
    main()
