"""Exchange-discipline comparison: BUFFERED vs COMPACT_BUFFERED vs UNBUFFERED.

Measures, per shard count P, each discipline's (a) off-shard wire bytes per
repartition (exact accounting from the plan geometry), (b) sequential
collective rounds, and (c) wall-clock per backward+forward pair — the
bytes-AND-latency picture the discipline choice actually trades off
(parallel/ragged.py LATENCY note). The reference offers the same three wire
disciplines but publishes no guidance numbers (reference:
include/spfft/types.h:33-62); this program produces them for a given plan.

On a virtual CPU mesh (default here) wall-clock is indicative only — CPU
"collectives" are memory copies, so the chain's extra rounds cost far less
than they do over ICI, and ragged-all-to-all falls back to the chain
transport. Run on a real pod slice for decision-grade timings.

Usage:
    python programs/discipline_compare.py [--shards 8 16 32] [--dim 64]
        [--sparsity 0.3] [--imbalance 0.0] [--repeats 20] [--json out.json]
        [--policy {default,tuned}]

``--imbalance w`` skews the per-shard stick weights linearly from 1 to 1+w,
exercising the regime where exact-counts disciplines win on bytes.

``--policy`` A/Bs the DEFAULT resolvers against the explicit disciplines: a
fourth row per shard count measures the plan a bare ``ExchangeType.DEFAULT``
produces under the selected policy — ``default`` (the analytic cost model,
parallel/policy.py) or ``tuned`` (the empirical autotuner, spfft_tpu.tuning;
CPU trials are auto-allowed here since this program measures on the virtual
CPU mesh anyway). The row records which discipline the policy resolved to and
its decision provenance, so model picks and wisdom picks can be compared
against the exhaustive sweep they should have matched.
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--shards", type=int, nargs="+", default=[8, 16, 32])
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--sparsity", type=float, default=0.3)
    ap.add_argument("--imbalance", type=float, default=0.0)
    ap.add_argument("--repeats", type=int, default=20)
    ap.add_argument("--engine", default="mxu", choices=["xla", "mxu"])
    ap.add_argument(
        "--policy", default="default", choices=["default", "tuned"],
        help="resolver measured for the extra DEFAULT row (see module doc)",
    )
    ap.add_argument("--json", default=None)
    args = ap.parse_args(argv)

    import jax

    max_p = max(args.shards)
    try:
        jax.config.update("jax_platforms", "cpu")
        # version-portable device-count knob (jax_num_cpu_devices or the
        # older XLA flag — parallel/mesh.configure_virtual_devices)
        from spfft_tpu.parallel.mesh import configure_virtual_devices

        configure_virtual_devices(max_p, warn=True)
    except Exception as e:
        print(f"late platform config ({e}); using visible devices", file=sys.stderr)

    import numpy as np
    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ExchangeType,
        ProcessingUnit,
        ScalingType,
        TransformType,
    )
    from spfft_tpu.parameters import distribute_triplets

    dim = args.dim
    rng = np.random.default_rng(0)
    triplets = sp.create_spherical_cutoff_triplets(dim, dim, dim, args.sparsity)
    values = (
        rng.standard_normal(len(triplets)) + 1j * rng.standard_normal(len(triplets))
    ).astype(np.complex64)

    disciplines = [
        ("BUFFERED", ExchangeType.BUFFERED),
        ("COMPACT", ExchangeType.COMPACT_BUFFERED),
        ("UNBUFFERED", ExchangeType.UNBUFFERED),
        # the A/B row: what a bare DEFAULT resolves to under --policy
        (f"DEFAULT:{args.policy}", ExchangeType.DEFAULT),
    ]
    if args.policy == "tuned":
        # this program already measures on the (virtual CPU) mesh, so CPU
        # trials cannot poison accelerator wisdom any more than the sweep does
        import os

        os.environ.setdefault("SPFFT_TPU_TUNE_CPU", "1")
    rows = []
    for P in args.shards:
        weights = 1.0 + args.imbalance * np.arange(P) / max(1, P - 1)
        per_shard = distribute_triplets(triplets, P, dim, weights=weights)
        vps = []
        order = {tuple(t): i for i, t in enumerate(map(tuple, triplets))}
        for p in per_shard:
            idx = [order[tuple(t)] for t in map(tuple, p)]
            vps.append(values[idx])
        mesh = sp.make_fft_mesh(P)
        for name, exchange in disciplines:
            t = DistributedTransform(
                ProcessingUnit.GPU if args.engine == "mxu" else ProcessingUnit.HOST,
                TransformType.C2C,
                dim,
                dim,
                dim,
                [p.copy() for p in per_shard],
                mesh=mesh,
                dtype=np.float32,
                engine=args.engine,
                exchange_type=exchange,
                # only the DEFAULT row resolves through a policy; explicit
                # disciplines are never overridden by either resolver
                policy=args.policy,
            )
            ex = t._exec
            pair = ex.pad_values(vps)
            out = t.backward_pair(*pair)  # compile both directions
            back = t.forward_pair(scaling=ScalingType.FULL)
            jax.block_until_ready((out, back))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                for _ in range(args.repeats):
                    out = t.backward_pair(*pair)
                    back = t.forward_pair(scaling=ScalingType.FULL)
                jax.block_until_ready((out, back))
                best = min(best, (time.perf_counter() - t0) / args.repeats)
            transport = getattr(ex._ragged, "transport", None)
            rows.append(
                {
                    "P": P,
                    "discipline": name,
                    "wire_bytes": ex.exchange_wire_bytes(),
                    "rounds": ex.exchange_rounds(),
                    "transport": transport,
                    "ms_per_pair": round(best * 1e3, 3),
                }
            )
            r = rows[-1]
            if exchange == ExchangeType.DEFAULT:
                rec = t._tuning
                r["resolved"] = t.exchange_type.name
                r["provenance"] = rec["provenance"] if rec else "model"
                if rec:
                    r["wisdom_hit"] = rec["hit"]
            print(
                f"P={P:3d} {name:16s} bytes={r['wire_bytes']:>12,} "
                f"rounds={r['rounds']:3d} {r['ms_per_pair']:8.2f} ms/pair"
                + (f" (transport={transport})" if transport else "")
                + (
                    f" -> {r['resolved']} [{r['provenance']}]"
                    if "resolved" in r
                    else ""
                )
            )
    if args.json:
        Path(args.json).write_text(json.dumps(
            {"config": vars(args), "rows": rows}, indent=2))
        print(f"wrote {args.json}")
    return rows


if __name__ == "__main__":
    main()
