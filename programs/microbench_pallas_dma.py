"""Pallas-DMA vs XLA-gather copy-plan apply — the round-5 A/B (VERDICT r4 #2).

The named-but-unattempted round-4 lever: replace the CopyPlan row gathers
with a Pallas kernel that DMAs rows directly. Context from this round's LANE
sweep (bench_results/round5_onchip.json c2c_512_sph15_r5_lane{128,256,512}):
widening rows 2x/4x (quartering the gather descriptor count) measured
NEUTRAL-to-worse at 512^3, so the gather's cost is not per-descriptor issue
overhead — this benchmark probes whether explicit DMA row moves beat
whatever the gather lowering actually does.

Arms (same (R rows out of M) x 128-lane geometry as the 512^3 decompress):
  1. jnp.take baseline (the CopyPlan aligned fast path),
  2. Pallas grid kernel: T-row VMEM output blocks, scalar-prefetched row
     indices, T in-flight HBM->VMEM row DMAs per program,
  3. Pallas HBM->HBM single-program kernel: fori_loop over rows with a
     ring of in-flight DMAs.

Chain-timed on chip (dependent iterations, scalar fence). Appends to
bench_results/round5_pallas_dma.json.
"""
from __future__ import annotations


import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_pallas_dma.json"
)

LANE = 128


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "microbench_pallas_dma", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    # 512^3 decompress-class geometry: gather R rows out of an (M, 128) table
    rng = np.random.default_rng(0)
    M = 735_000   # ~ S*Z/LANE source rows at 512^3/15% (value flats)
    R = 360_448   # destination rows (stick table blocks), 8-divisible
    idx = np.sort(rng.choice(M, size=R, replace=False)).astype(np.int32)
    src = jnp.asarray(rng.standard_normal((M, LANE)).astype(np.float32))
    idx_t = jnp.asarray(idx)

    REPS = 32

    def timed(name, fn, *args, extra=None):
        @jax.jit
        def loop(s):
            def body(carry, _):
                out = fn(carry, *args)
                # dependent chain: fold output back into a source-shaped
                # carry via one cheap dynamic slice write
                return carry.at[:LANE, :].set(out[:LANE, :]), ()

            final, _ = jax.lax.scan(body, s, None, length=REPS)
            return final.ravel()[0]

        try:
            float(jax.device_get(loop(src)))
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                out = loop(src)
                float(jax.device_get(out))
                best = min(best, (time.perf_counter() - t0) / REPS)
            row = {"name": name, "ms": round(best * 1e3, 3),
                   "ns_per_row": round(best / R * 1e9, 2)}
            if extra:
                row.update(extra)
            record(row)
            return best
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})
            return None

    # ---- 1: jnp.take baseline ----
    timed("xla_take", lambda s: jnp.take(s, idx_t, axis=0))

    # ---- 2: Pallas grid kernel, T rows per program ----
    def make_grid_kernel(T):
        def kernel(idx_ref, src_ref, out_ref, sems):
            i = pl.program_id(0)
            for j in range(T):
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[i * T + j]],
                    out_ref.at[j],
                    sems.at[j],
                ).start()
            for j in range(T):
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[i * T + j]],
                    out_ref.at[j],
                    sems.at[j],
                ).wait()

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(R // T,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(
                (T, LANE), lambda i, idx_ref: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            scratch_shapes=[pltpu.SemaphoreType.DMA((T,))],
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, LANE), jnp.float32),
            grid_spec=grid_spec,
        )

    for T in (32, 128, 512):
        try:
            k = make_grid_kernel(T)
            timed(f"pallas_grid_T{T}", lambda s, k=k: k(idx_t, s),
                  extra={"T": T})
        except Exception as e:
            record({"name": f"pallas_grid_T{T}",
                    "error": f"{type(e).__name__}: {e}"})

    # ---- 3: Pallas single-program HBM->HBM ring ----
    def make_ring_kernel(NSEM):
        def kernel(idx_ref, src_ref, out_ref, sems):
            def issue(r, _):
                slot = jax.lax.rem(r, NSEM)
                # wait the previous DMA occupying this semaphore slot
                @pl.when(r >= NSEM)
                def _():
                    prev = r - NSEM
                    pltpu.make_async_copy(
                        src_ref.at[idx_ref[prev]], out_ref.at[prev],
                        sems.at[slot],
                    ).wait()

                pltpu.make_async_copy(
                    src_ref.at[idx_ref[r]], out_ref.at[r], sems.at[slot]
                ).start()
                return ()

            jax.lax.fori_loop(0, R, issue, ())

            def drain(k, _):
                r = R - NSEM + k
                slot = jax.lax.rem(r, NSEM)
                pltpu.make_async_copy(
                    src_ref.at[idx_ref[r]], out_ref.at[r], sems.at[slot]
                ).wait()
                return ()

            jax.lax.fori_loop(0, NSEM, drain, ())

        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(1,),
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
            scratch_shapes=[pltpu.SemaphoreType.DMA((NSEM,))],
        )
        return pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct((R, LANE), jnp.float32),
            grid_spec=grid_spec,
        )

    for NSEM in (8, 32):
        try:
            k = make_ring_kernel(NSEM)
            timed(f"pallas_ring_N{NSEM}", lambda s, k=k: k(idx_t, s),
                  extra={"NSEM": NSEM})
        except Exception as e:
            record({"name": f"pallas_ring_N{NSEM}",
                    "error": f"{type(e).__name__}: {e}"})

    # ---- context: contiguous-slice ceiling (what a perfect copy costs) ----
    timed("contiguous_slice", lambda s: jax.lax.slice(s, (0, 0), (R, LANE)))

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
