"""Benchmark CLI — the rebuilt harness of the reference's ``benchmark`` program
(reference: tests/programs/benchmark.cpp).

Same flag surface (`-d X Y Z -r repeats -o out.json -s sparsity -t c2c|r2c
-e exchange -p cpu|gpu -m numTransforms`), same stick-generation model
(x in [0, dimXFreq*sparsity), full y column set, x==0 limited to dimYFreq for R2C,
contiguous even distribution over shards — reference: benchmark.cpp:177-205), warm-up
run then a timed backward+forward loop (reference: benchmark.cpp:63-96), and a JSON
report bundling parameters, measured results, and the nested timing tree
(reference: benchmark.cpp:283-307).

Additions forced by TPU semantics: on the tunneled TPU platform
``block_until_ready`` does not wait for execution, so wall-clock is measured by
chaining R *dependent* roundtrips (forward output feeds the next backward) inside
one compiled ``lax.scan`` (single dispatch; sustained throughput) and fetching a
scalar at the end; with FULL scaling the chain is an identity so results stay
bounded. ``--shards N`` runs the mesh-distributed path (the reference's MPI
ranks), on real devices or a virtual CPU mesh.

Usage examples:
  python programs/benchmark.py -d 128 128 128 -r 20 -s 0.3 -t c2c -e compact -p cpu -o out.json
  python programs/benchmark.py -d 256 256 256 -r 10 -p gpu --shards 4 -e buffered -o out.json
"""
from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np


EXCHANGE_NAMES = {
    "buffered": "BUFFERED",
    "bufferedFloat": "BUFFERED_FLOAT",
    "compact": "COMPACT_BUFFERED",
    "compactFloat": "COMPACT_BUFFERED_FLOAT",
    "unbuffered": "UNBUFFERED",
    # TPU extensions: explicit bf16 wire (see spfft_tpu/types.py ExchangeType).
    "bufferedBF16": "BUFFERED_BF16",
    "compactBF16": "COMPACT_BUFFERED_BF16",
}


def create_benchmark_triplets(dim_x, dim_y, dim_z, sparsity, r2c):
    """The reference benchmark's stick set (reference: benchmark.cpp:177-205):
    all (x, y) with x < dimXFreq*sparsity; for R2C, the x==0 sticks cover only
    y < dimYFreq (hermitian non-redundant half)."""
    dim_x_freq = dim_x // 2 + 1 if r2c else dim_x
    dim_y_freq = dim_y // 2 + 1 if r2c else dim_y
    xs = np.arange(int(np.ceil(dim_x_freq * sparsity)) or 1, dtype=np.int32)
    xy = np.concatenate(
        [
            np.stack(
                [
                    np.full(dim_y_freq if x == 0 else dim_y, x, dtype=np.int32),
                    np.arange(dim_y_freq if x == 0 else dim_y, dtype=np.int32),
                ],
                axis=1,
            )
            for x in xs
        ]
    )
    zs = np.arange(dim_z, dtype=np.int32)
    trips = np.empty((len(xy), dim_z, 3), dtype=np.int32)
    trips[:, :, 0] = xy[:, None, 0]
    trips[:, :, 1] = xy[:, None, 1]
    trips[:, :, 2] = zs[None, :]
    return trips.reshape(-1, 3), len(xy)


def split_contiguous(triplets, num_sticks, num_shards, dim_z):
    """Even contiguous stick distribution over shards (reference: benchmark.cpp:190-205)."""
    per = [
        num_sticks // num_shards + (1 if r < num_sticks % num_shards else 0)
        for r in range(num_shards)
    ]
    out, pos = [], 0
    for n in per:
        out.append(triplets[pos * dim_z : (pos + n) * dim_z])
        pos += n
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description="sparse 3D FFT benchmark")
    ap.add_argument("-d", nargs=3, type=int, required=True, metavar=("X", "Y", "Z"))
    ap.add_argument("-r", type=int, required=True, help="number of repeats")
    ap.add_argument("-o", type=str, required=True, help="output JSON file")
    ap.add_argument("-m", type=int, default=1, help="multiple transform number")
    ap.add_argument("-s", type=float, default=1.0, help="sparsity")
    ap.add_argument("-t", choices=["c2c", "r2c"], default="c2c")
    ap.add_argument(
        "-e",
        choices=sorted(EXCHANGE_NAMES) + ["all"],
        default="buffered",
        help="exchange type (distributed runs)",
    )
    ap.add_argument("-p", choices=["cpu", "gpu", "gpu-gpu"], required=True)
    ap.add_argument("--shards", type=int, default=1, help="mesh size (1 = local)")
    ap.add_argument(
        "--mesh2", nargs=2, type=int, default=None, metavar=("P1", "P2"),
        help="2-D pencil mesh factors (selects the pencil engine; overrides --shards)",
    )
    ap.add_argument(
        "--precision", choices=["single", "double"], default=None,
        help="default: double on cpu, single on accelerators",
    )
    ap.add_argument(
        "--engine", choices=["auto", "mxu", "xla"], default="auto",
        help="local execution engine (default: auto-select)",
    )
    ap.add_argument(
        "--model", choices=["xslab", "spherical"], default="xslab",
        help="stick model: xslab = reference benchmark's x < Xf*s slab "
        "(benchmark.cpp:177-205); spherical = centered spherical cutoff with "
        "nonzero fraction ~= s (the plane-wave DFT workload)",
    )
    ap.add_argument(
        "--matmul-precision", choices=["highest", "high"], default="highest",
        help="MXU engine matmul precision (high trades ~1e-5 accuracy for speed)",
    )
    args = ap.parse_args(argv)
    if args.mesh2 is not None:
        p1, p2 = args.mesh2
        if p1 < 1 or p2 < 1 or p1 * p2 < 2:
            ap.error("--mesh2 factors must be >= 1 with product >= 2")
        args.shards = p1 * p2

    import os

    import jax

    if args.precision == "double" or (args.precision is None and args.p == "cpu"):
        jax.config.update("jax_enable_x64", True)
        dtype = np.float64
    else:
        dtype = np.float32
    # Virtual CPU mesh for distributed runs on a single host (the reference's
    # ``mpirun -n N`` on one CI VM): size the CPU platform before first backend use.
    if args.shards > 1 and (args.p == "cpu" or os.environ.get("JAX_PLATFORMS", "") == "cpu"):
        # shared bootstrap: tolerates an already-initialized backend (e.g. when
        # main() is driven in-process after other JAX work) with a stderr note
        from spfft_tpu.parallel.mesh import configure_virtual_devices

        configure_virtual_devices(args.shards, warn=True)

    import spfft_tpu as sp
    from spfft_tpu import timing
    from spfft_tpu.execution import as_pair
    from spfft_tpu.types import ExchangeType, ProcessingUnit, ScalingType, TransformType

    timing.enable()

    dim_x, dim_y, dim_z = args.d
    r2c = args.t == "r2c"
    ttype = TransformType.R2C if r2c else TransformType.C2C
    pu = ProcessingUnit.HOST if args.p == "cpu" else ProcessingUnit.GPU
    # "-e all" sweeps every exchange variant over the same plan geometry, like the
    # reference benchmark; local runs have no exchange so it degenerates to one run.
    if args.mesh2 is not None:
        # the pencil engine implements the padded BUFFERED discipline only
        pencil_ok = {"buffered", "bufferedFloat", "bufferedBF16"}
        if args.e == "all":
            exchange_sweep = sorted(pencil_ok)
        elif args.e in pencil_ok:
            exchange_sweep = [args.e]
        else:
            ap.error(f"--mesh2 supports only {sorted(pencil_ok)} for -e")
    elif args.shards > 1:
        exchange_sweep = sorted(EXCHANGE_NAMES) if args.e == "all" else [args.e]
    else:
        exchange_sweep = [args.e if args.e != "all" else "buffered"]

    if args.model == "spherical":
        radius = sp.spherical_radius_for_fraction(args.s)
        if radius > 1.0:
            # beyond s = pi/6 the ball is clipped by the cube; the report records
            # the *effective* nonzero fraction below, not the requested s
            print(f"note: -s {args.s} exceeds the inscribed ball (pi/6); clipping")
        triplets = sp.create_spherical_cutoff_triplets(
            dim_x, dim_y, dim_z, radius, hermitian_symmetry=r2c
        )
        from spfft_tpu.parameters import stick_keys

        num_sticks = len(np.unique(stick_keys(triplets, dim_y)))
    else:
        triplets, num_sticks = create_benchmark_triplets(
            dim_x, dim_y, dim_z, args.s, r2c
        )
    rng = np.random.default_rng(42)

    def build_transforms(exchange_name):
        exchange = ExchangeType[EXCHANGE_NAMES[exchange_name]]
        with timing.scoped("Grid + Transform init"):
            if args.shards > 1:
                # -p cpu must mesh over the (virtual) CPU devices even when an
                # accelerator is attached as the default backend.
                mesh_devices = (
                    jax.devices("cpu")[: args.shards] if args.p == "cpu" else None
                )
                if args.mesh2 is not None:
                    mesh = sp.make_fft_mesh2(*args.mesh2, devices=mesh_devices)
                else:
                    mesh = sp.make_fft_mesh(args.shards, devices=mesh_devices)
                if args.model == "spherical":
                    # variable-length sticks: balanced whole-stick partition
                    per_shard = sp.distribute_triplets(triplets, args.shards, dim_y)
                else:
                    per_shard = split_contiguous(triplets, num_sticks, args.shards, dim_z)
                return [
                    sp.DistributedTransform(
                        pu, ttype, dim_x, dim_y, dim_z, [t.copy() for t in per_shard],
                        mesh=mesh, exchange_type=exchange, dtype=dtype,
                        engine=args.engine, precision=args.matmul_precision,
                    )
                    for _ in range(args.m)
                ]
            return [
                sp.Transform(
                    pu, ttype, dim_x, dim_y, dim_z, indices=triplets, dtype=dtype,
                    engine=args.engine, precision=args.matmul_precision,
                )
                for _ in range(args.m)
            ]

    def make_values(t):
        if r2c:  # hermitian-consistent inputs: derive from a real field
            space = rng.standard_normal((dim_z, dim_y, dim_x))
            return t.forward(space, ScalingType.NONE)
        if args.shards > 1:
            return [
                rng.standard_normal(t.num_local_elements(r))
                + 1j * rng.standard_normal(t.num_local_elements(r))
                for r in range(t.num_shards)
            ]
        n = t.num_local_elements
        return rng.standard_normal(n) + 1j * rng.standard_normal(n)

    def fence(scalar):
        """Force completion with ONE scalar fetch (axon TPU: block_until_ready
        does not wait). The scalar is reduced inside the compiled program —
        eager device-side slicing per transform would add several tunnel
        round-trips (~2-40 ms each) per fence, dominating small timed loops."""
        _ = float(scalar)

    def measure(exchange_name):
        transforms = build_transforms(exchange_name)
        values = [make_values(t) for t in transforms]

        # Warm-up (compilation; reference: benchmark.cpp:63-70).
        with timing.scoped("warmup"):
            sp.multi_transform_backward(transforms, values)
            sp.multi_transform_forward(transforms, None, ScalingType.FULL)

        # Timed loop (reference: benchmark.cpp:84-96). Chained dependent roundtrips
        # so platforms with fire-and-forget dispatch are timed correctly.
        ex = [t._exec for t in transforms]
        freq_pairs = []
        for t, v in zip(transforms, values):
            if args.shards > 1:
                freq_pairs.append(t._exec.pad_values(v))
            else:
                re, im = as_pair(v, dtype)
                freq_pairs.append((t._exec.put(re), t._exec.put(im)))

        # rotation tables as jit operands (ops/lanecopy.phase_rep_operands) —
        # the embedded-constant form overflows the compile transport at 512^3,
        # so they thread through the outer jit's argument list
        phase_args = [getattr(e, "phase_operands", ()) for e in ex]

        def roundtrip_chain(pairs, phases):
            # trace_* (un-jitted impls): a jit boundary inside the scan body
            # blocks cross-stage fusion (measured ~30% slower per pair).
            outs = []
            for e, ph, (re, im) in zip(ex, phases, pairs):
                space = e.trace_backward(re, im, phase=ph)
                if r2c:
                    outs.append(
                        e.trace_forward(space, None, ScalingType.FULL, phase=ph)
                    )
                else:
                    sre, sim = space
                    outs.append(
                        e.trace_forward(sre, sim, ScalingType.FULL, phase=ph)
                    )
            return outs

        # All r repeats run inside ONE compiled lax.scan so a single dispatch
        # covers the whole timed loop: this measures sustained device throughput
        # rather than billing per-call dispatch latency (tens of ms through the
        # development tunnel; sub-ms on directly attached hardware) to every
        # pair. The repeats remain *dependent* roundtrips, exactly like the
        # reference's repeated in-place loop (reference: benchmark.cpp:84-96).
        def scan_chain(pairs, phases):
            def body(carry, _):
                return tuple(roundtrip_chain(list(carry), phases)), None
            out, _ = jax.lax.scan(body, tuple(pairs), None, length=args.r)
            # single fence scalar, reduced in-program (see fence())
            return sum(p[0].ravel()[0] + p[1].ravel()[0] for p in out)

        jitted = jax.jit(scan_chain)

        # Warm the exact timed artifact: AOT-compile the fused roundtrip chain,
        # then execute it ONCE untimed. Both steps are required for a clean
        # measurement: `jitted(...)` in the timed section would re-pay tracing +
        # lowering (lower().compile() does not populate the jit call cache), and
        # the FIRST execution of a compiled executable pays one-time program
        # load + constant upload through the device tunnel (measured 60-400
        # ms/pair at 128^3 vs 5-7 ms steady-state). This mirrors the
        # reference's executed warm-up run (reference: benchmark.cpp:63-70).
        with timing.scoped("warmup chain"):
            compiled = jitted.lower(freq_pairs, phase_args).compile()
            fence(compiled(freq_pairs, phase_args))

        with timing.scoped("benchmark loop"):
            start = time.perf_counter()
            checksum = compiled(freq_pairs, phase_args)
            fence(checksum)
            elapsed = time.perf_counter() - start

        pair_seconds = elapsed / (args.r * args.m)
        n_total = dim_x * dim_y * dim_z
        # Standard 5 N log2(N) flop model per 3D transform; x2 for fwd+bwd pair.
        flops = 2 * 5.0 * n_total * np.log2(n_total)
        from spfft_tpu.tuning import wisdom_state

        out = {
            "wall_s_total": elapsed,
            "wall_s_per_transform_pair": pair_seconds,
            "gflops_per_pair": flops / pair_seconds / 1e9,
            # decision provenance: what this plan chose (spfft_tpu.obs) and
            # how — policy, model-vs-wisdom, store path, hit/miss
            # (spfft_tpu.tuning) — so numbers are reproducible
            "plan": transforms[0].report(),
            "wisdom": wisdom_state(transforms[0]),
        }
        if args.shards > 1:
            # off-shard interconnect bytes per repartition under this discipline
            out["exchange_wire_bytes"] = transforms[0].exchange_wire_bytes()
        return out

    results = {name: measure(name) for name in exchange_sweep}

    report = {
        "parameters": {
            "dim_x": dim_x, "dim_y": dim_y, "dim_z": dim_z,
            "sparsity": args.s,
            "effective_nnz_fraction": float(
                len(triplets) / (dim_x * dim_y * dim_z)
            ),
            "num_z_sticks": num_sticks,
            "num_elements": int(len(triplets)),
            "transform_type": args.t,
            "processing_unit": args.p,
            "exchange": exchange_sweep if len(exchange_sweep) > 1 else exchange_sweep[0],
            "precision": "double" if dtype == np.float64 else "single",
            "num_transforms": args.m,
            "repeats": args.r,
            "shards": args.shards,
            "mesh2": args.mesh2,
            "backend": jax.default_backend(),
            "device": str(jax.devices()[0]),
        },
        "results": results[exchange_sweep[0]] if len(exchange_sweep) == 1 else results,
        "timings": timing.process().to_dict(),
    }
    Path(args.o).write_text(json.dumps(report, indent=2))
    print(json.dumps({k: report[k] for k in ("parameters", "results")}, indent=2))


if __name__ == "__main__":
    main()
