"""Round-5 on-chip batch 3: R2C blocked sparse-y rows + copy-plan LANE sweep.

1. R2C spherical rows with the round-5 engine (blocked sparse-y now covers
   R2C via the dense-x0-bucket extension — VERDICT r4 item 3): 128^3 and
   512^3, blocked-auto vs blocked-off arms (one variable).
2. Copy-plan LANE width at 512^3 (VERDICT r4 item 2, the descriptor floor):
   at Z = 512 the Z %% LANE == 0 alignment precondition holds for LANE = 256
   AND 512 (the round-3 rejection was measured at 256^3 where Z = 256 breaks
   LANE = 512). Wider lanes quarter the gather descriptor count — decompress
   is 15.6 ms of the 46 ms 512^3 backward at ~25 ns/row. Arms: LANE 128
   (default re-pin), 256, 512 at 512^3 C2C sph15; plus 256^3 C2C LANE=256
   re-check (expected noise, pins the scale dependence).

Appends to bench_results/round5_onchip.json.
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_onchip.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round5_measurements3", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import os

    import spfft_tpu as sp
    from spfft_tpu import (
        ProcessingUnit,
        ScalingType,
        Transform,
        TransformType,
    )
    from spfft_tpu.ops import lanecopy

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def flops_pair(dim):
        n = dim**3
        return 2 * 5.0 * n * np.log2(n)

    def chain_time(ex, re0, im0, chain, r2c=False):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                if r2c:
                    space = ex.trace_backward(carry[0], carry[1], phase=ph)
                    out = ex.trace_forward(space, None, ScalingType.FULL, phase=ph)
                else:
                    sre, sim = ex.trace_backward(*carry, phase=ph)
                    out = ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph)
                return out, None

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, _ = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, _ = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    def with_env(envs, fn):
        saved = {k: os.environ.get(k) for k in envs}
        for k, v in envs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        try:
            return fn()
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def spherical_r2c_trip(dim):
        trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
        return trip[trip[:, 0] >= 0]  # hermitian non-redundant half

    def measure(name, dim, ttype, chain, env=None, lane=None):
        def run():
            orig_lane = lanecopy.LANE
            if lane is not None:
                lanecopy.LANE = lane
            try:
                if ttype == TransformType.R2C:
                    trip = spherical_r2c_trip(dim)
                else:
                    trip = sp.create_spherical_cutoff_triplets(
                        dim, dim, dim, 0.659
                    )
                t = Transform(
                    ProcessingUnit.GPU, ttype, dim, dim, dim,
                    indices=trip, dtype=np.float32, engine="mxu",
                )
                ex = t._exec
                rng = np.random.default_rng(0)
                n = len(trip)
                re0 = ex.put(rng.standard_normal(n).astype(np.float32))
                im0 = ex.put(rng.standard_normal(n).astype(np.float32))
                best, err = chain_time(
                    ex, re0, im0, chain, r2c=ttype == TransformType.R2C
                )
                record({
                    "name": name, "dim": dim, "chain": chain,
                    "ms_per_pair": round(best * 1e3, 3),
                    "gflops": round(flops_pair(dim) / best / 1e9, 1),
                    "roundtrip_err": err,
                    "blocked_buckets": len(ex._sparse_y_blocked or ()),
                    "x0_bucket": ex._sy_x0_bucket,
                    "lane": lane or 128,
                })
            finally:
                lanecopy.LANE = orig_lane

        try:
            with_env(env or {}, run)
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})

    R2C = TransformType.R2C
    C2C = TransformType.C2C

    # ---- 1: R2C blocked arms ----
    measure("r2c_128_sph15_r5_blocked", 128, R2C, 768)
    measure(
        "r2c_128_sph15_r5_blocked_off", 128, R2C, 768,
        env={"SPFFT_TPU_SPARSE_Y_BLOCKS": "0"},
    )
    measure("r2c_512_sph15_r5_blocked", 512, R2C, 48)
    measure(
        "r2c_512_sph15_r5_blocked_off", 512, R2C, 48,
        env={"SPFFT_TPU_SPARSE_Y_BLOCKS": "0"},
    )

    # ---- 2: LANE sweep at 512^3 (Z % 512 == 0 holds there) ----
    measure("c2c_512_sph15_r5_lane128", 512, C2C, 48)
    measure("c2c_512_sph15_r5_lane256", 512, C2C, 48, lane=256)
    measure("c2c_512_sph15_r5_lane512", 512, C2C, 48, lane=512)
    # 256^3 scale re-check (expected ~noise per round 3)
    measure("c2c_256_s15_r5_lane256", 256, C2C, 384, lane=256)

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
