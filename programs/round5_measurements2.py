"""Round-5 on-chip batch 2: pencil re-measure after the ragged-exchange fix.

Batch 1 + bisection found the 980 ms 1x1-pencil cost in the block exchanges'
element-granular pack/unpack (RaggedBlockExchange flat exact-product buffers,
~20 ns/element; bench_results/round5_pencil_bisect2.json). Both block
exchange classes are now row-granular (2-D dynamic-slice chain windows /
C-row ragged units). This batch re-pins the pencil arms:

1. 1x1 COMPACT (what DEFAULT resolves to at P=1) — the headline fix check
   against the 5.461 ms local arm (done = within ~1.5x),
2. 1x1 BUFFERED (exchange specialized away entirely) — isolates any residual
   non-exchange pencil overhead.

Appends to bench_results/round5_onchip.json (same file as batch 1).
"""
from __future__ import annotations

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

OUT = (
    Path(__file__).resolve().parent.parent
    / "bench_results"
    / "round5_onchip.json"
)


def main():
    import numpy as np

    from spfft_tpu._platform import hang_watchdog

    disarm = hang_watchdog(
        "round5_measurements2", "SPFFT_TPU_MEASURE_INIT_BUDGET_S", 900,
        exit_code=2,
    )
    import jax

    dev = jax.devices()[0]
    print(f"backend ready: {dev}", file=sys.stderr)
    disarm()

    import spfft_tpu as sp
    from spfft_tpu import (
        DistributedTransform,
        ExchangeType,
        ProcessingUnit,
        ScalingType,
        TransformType,
    )

    results = []
    if OUT.exists():
        try:
            results = json.loads(OUT.read_text())
        except Exception:
            results = []

    def record(row):
        results.append(row)
        OUT.write_text(json.dumps(results, indent=2))
        print(json.dumps(row), flush=True)

    def flops_pair(dim):
        n = dim**3
        return 2 * 5.0 * n * np.log2(n)

    def chain_time(ex, re0, im0, chain):
        phase = getattr(ex, "phase_operands", ())

        def chain_fn(r, i, ph):
            def body(carry, _):
                sre, sim = ex.trace_backward(*carry, phase=ph)
                return ex.trace_forward(sre, sim, ScalingType.FULL, phase=ph), None

            return jax.lax.scan(body, (r, i), None, length=chain)[0]

        step = jax.jit(chain_fn)
        wre, _ = step(re0, im0, phase)
        np.asarray(jax.device_get(wre.ravel()[0]))
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            cre, _ = step(re0, im0, phase)
            float(jax.device_get(cre.ravel()[0]))
            best = min(best, (time.perf_counter() - t0) / chain)
        err = float(
            np.abs(np.asarray(cre).ravel()[:64] - np.asarray(re0).ravel()[:64]).max()
        )
        return best, err

    dim = 256
    LOCAL_MS = 5.461  # batch-1 matched local arm
    trip = sp.create_spherical_cutoff_triplets(dim, dim, dim, 0.659)
    rng = np.random.default_rng(0)

    for name, exchange, chain in (
        ("pencil1x1_c2c_256_sph15_r5_fixed", ExchangeType.DEFAULT, 48),
        ("pencil1x1_c2c_256_sph15_r5_buffered", ExchangeType.BUFFERED, 48),
    ):
        try:
            t = DistributedTransform(
                ProcessingUnit.GPU, TransformType.C2C, dim, dim, dim, trip,
                mesh=sp.make_fft_mesh2(1, 1), dtype=np.float32, engine="mxu",
                exchange_type=exchange,
            )
            ex = t._exec
            vals = (
                rng.standard_normal(t.num_local_elements(0))
                + 1j * rng.standard_normal(t.num_local_elements(0))
            ).astype(np.complex64)
            pairs = ex.pad_values([vals])
            best, err = chain_time(ex, pairs[0], pairs[1], chain)
            row = {
                "name": name, "chain": chain,
                "ms_per_pair": round(best * 1e3, 3),
                "gflops": round(flops_pair(dim) / best / 1e9, 1),
                "roundtrip_err": err,
                "resolved_exchange": str(t.exchange_type),
                "vs_local": round(best * 1e3 / LOCAL_MS, 3),
            }
            record(row)
            if best * 1e3 < 50:
                best, err = chain_time(ex, pairs[0], pairs[1], 384)
                record({**row, "name": name + "_long", "chain": 384,
                        "ms_per_pair": round(best * 1e3, 3),
                        "gflops": round(flops_pair(dim) / best / 1e9, 1),
                        "roundtrip_err": err,
                        "vs_local": round(best * 1e3 / LOCAL_MS, 3)})
        except Exception as e:
            record({"name": name, "error": f"{type(e).__name__}: {e}"})

    print(f"wrote {OUT}", file=sys.stderr)


if __name__ == "__main__":
    main()
